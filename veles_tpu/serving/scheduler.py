"""Continuous-batching inference scheduler.

Requests queue on :meth:`InferenceScheduler.submit` (any thread) and
are decoded by ONE background loop (all jax work — ``Array.devmem``
uploads and the compile caches are not thread-safe against concurrent
mutation, and a single loop is what lets every in-flight request share
one compiled step):

1. **admit** — while capacity allows, the oldest queued request
   claims a slot.  Under the default PAGED KV cache
   (:class:`serving.kv_slots.PagedKVCache`) admission is
   memory-proportional: the request also claims its whole block
   budget (``ceil((prompt + steps) / block_size)`` blocks), so short
   requests pack many more concurrent streams into the same HBM than
   the dense window-per-slot layout;
2. **prefill** — prompts up to ``prefill_chunk`` prefill in ONE
   compiled pass; longer prompts prefill in ``prefill_chunk``-token
   CHUNKS, at most one chunk per loop iteration, INTERLEAVED with the
   decode step below (Sarathi-style chunked prefill) — a joining long
   prompt stalls in-flight decode streams by one chunk per iteration,
   not by its whole prefill, which flattens the TTFT tail of short
   requests stuck behind long ones.  Either way the K/V staging row
   is inserted into the cache and the first token samples from the
   final logits (the TTFT edge);
3. **step** — active slots advance one token through the shared
   compiled step.  The paged path packs ONLY the active slots into a
   power-of-two occupancy bucket and bounds attention by a
   power-of-two block bucket over the deepest request
   (:func:`serving.engine.paged_decode_step`), so a half-empty batch
   of shallow requests pays neither full-batch nor full-window
   compute; the dense fallback runs the fixed full-slot step;
4. **retire** — a slot that generated its stop token or hit its step
   limit completes its future and frees slot + blocks at the token
   boundary, where the next queued request joins.

Admission control: a full queue raises :class:`QueueFullError` (HTTP
503) at submit; a request still queued past its deadline fails with
:class:`DeadlineExceededError` (HTTP 408).  Greedy requests keep
exact determinism (each request's attention sees only its own cache
rows/blocks, and sampling is row-wise, so token streams are
independent of slot placement, packing order and co-tenants);
sampled requests are reproducible per seed — though the stream
differs from the single-user ``generate()`` path's (one fold per
generated token here vs one split per lockstep buffer position
there).

Request lifecycle (fault tolerance): every request carries a
whole-request **deadline** (``root.common.serving.request_timeout``,
overridable per submit) enforced at chunk/decode boundaries — an
expired request frees its slot and blocks and fails with
:class:`DeadlineExceededError` carrying the tokens generated so far
(HTTP 408 material).  A client that went away can :meth:`cancel` its
future; the loop releases the resources at the next boundary.  The
scheduler can **preempt** an active request
(:meth:`request_preempt`): its blocks return to the pool, its
generated-token prefix is kept, and on re-admission prompt + prefix
re-prefill through the chunked-prefill path and decoding continues —
the token stream is bit-identical to the uninterrupted run because
token ``t`` is always drawn with ``fold_in(key(seed), t)`` regardless
of slot or cache placement.  A **watchdog** thread detects a stuck
decode step (``root.common.serving.watchdog`` seconds) and fails
pending requests instead of hanging their clients; block-pressure
**load shedding** (``shed_block_factor``) turns hopeless submits into
deterministic 503s before they queue; and :meth:`drain` closes
admission (503 + Retry-After), finishes everything in flight and
signals ``drained`` — the rolling-restart hook behind ``POST
/drain``.  Injection points (``serving.scheduler.*`` — see
:mod:`veles_tpu.faults`) let tier-1 exercise every one of these paths
deterministically.

Decode speed (both paged-only, off by default): **speculative
decoding** (``spec`` + ``spec_k``) drafts up to k tokens per slot by
n-gram prompt lookup (:mod:`veles_tpu.serving.spec`) and scores the
pending token plus all drafts in ONE batched verify pass
(:func:`serving.engine.verify_step_paged`) — the accepted prefix
plus the correction sample reproduces the spec-off stream
bit-for-bit (greedy AND seeded; the verify samples fold the same
per-request draw counters), rejected tails roll back logically
(their K/V rows sit past the accepted length, masked until
overwritten), and the occupancy/depth bucket ladder grows a draft
axis: ONE fixed ``spec_k``-wide verify executable per (B, T) for
n-gram-only schedulers (shorter draft sets pad and ``lens`` masks
them — the pre-PR 20 compile count), while model-drafter schedulers
(``draft_head`` attached) key the width on the power-of-two bucket
of the widest per-slot adaptive ``draft_k`` so collapsed-accept-rate
batches stop paying ``spec_k``-wide sampling.  The **radix
prefix cache** (``prefix_cache`` + ``prefix_evict``;
:mod:`veles_tpu.serving.prefix_cache`) makes KV blocks
cross-request: finished requests donate their written blocks,
admission longest-prefix-matches the trie so warm prompts gather
the resident rows and chunk-prefill only the cold tail, claim only
``ceil(cold_tokens / block_size)`` new blocks (cache hits raise max
concurrent streams), and refcount-0 residents LRU-evict under pool
pressure.

Delivery and QoS (the streaming/priority layer, see
:mod:`veles_tpu.serving.streams`): ``submit(..., stream=True)``
returns a :class:`~veles_tpu.serving.streams.TokenStream` the decode
loop pushes every ACCEPTED token into at the same boundary it appends
to ``generated`` — per-token latency for clients, spec bursts back to
back, nothing emitted twice across a preempt→resume.  Every request
carries a **priority class** (``low`` / ``normal`` / ``high``, default
normal): the queue is ordered by class (FIFO within one), block-
pressure shedding trips EARLIER for lower classes (the 503's
Retry-After also grows as the class drops), a full queue evicts the
youngest queued lower-class request to seat a higher one, and a
high-class arrival that cannot admit preempts the youngest active
LOWER-class request through the generalized
:meth:`request_preempt` victim selection — the victim resumes
bit-identically (the PR 7 contract), it just waits out the burst.
Per-class TTFT/preempt/shed counters ride
``veles_serving_class_*``.

Config knobs (``root.common.serving.*``, overridable per scheduler):
``kv`` ("paged"/"dense"), ``block_size`` (tokens per KV block,
default 16), ``kv_blocks`` (pool capacity in blocks; default the
dense-equivalent ``max_slots · ceil(window / block_size)``),
``kv_dtype`` ("fp32" default — the bit-parity baseline — or "int8":
paged pools stored quantized with per-row scales beside the block
tables, roughly halving bytes per cached token so the same HBM
budget decodes ~2x the concurrent streams; quality-gated by
``serving/kv_quality.py`` and ``quality.py``'s kv_quant record.
Under int8 a preempt→resume continues within quantization noise
rather than bit-identically — the re-prefill computes deeper
layers from f32 staging attention where the original decode read
dequantized keys — while warm radix resubmits stay exact because
matched blocks are REUSED, not recomputed; the fp32 default keeps
every PR 7 bit-exactness contract),
``prefill_chunk`` (chunk width in tokens, rounded up to a power of
two; 0 disables chunking, default 64), ``request_timeout`` /
``watchdog`` / ``shed_block_factor`` (lifecycle knobs above; 0
disables each), ``spec`` / ``spec_k`` (speculative decoding),
``fused_verify`` (score the spec run single-pass instead of the
scatter-then-gather two-pass — allclose, not bit-identical, so the
parity baseline keeps it off; int8 pools always verify fused),
``prefix_cache`` / ``prefix_evict`` (the radix cache above).

Scale-out (both off by default): **tensor-parallel serving**
(``tp`` / ``root.common.serving.tp``; :mod:`veles_tpu.serving.tp`)
shards every jitted step — chunked prefill, the paged decode step,
the spec verify step and the ``serving.kv_*`` block movers — over a
``{"tp": N}`` mesh with Megatron column/row weight splits and
HEAD-WISE paged pools (each chip stores ``[blocks, bs, d/tp]``, int8
scales replicated), so the per-chip HBM of a ``kv_blocks`` budget
drops by the mesh factor and a model too wide for one chip still
serves; block tables, admission, the radix trie, drafting and this
loop stay replicated host logic.  **Disaggregated prefill/decode**
(``role`` / ``root.common.serving.role``): a ``"prefill"``-role
scheduler accepts only :meth:`submit_prefill` — it chunk-prefills,
gathers the finished blocks raw (scales riding along) and parks the
record for ``GET /serving/kv_export/<handle>``; a ``"decode"``-role
scheduler adopts such records via :meth:`submit_imported` — blocks
scatter straight into its own table and the first token samples from
the exported last-position logits, so the stream is identical to the
colocated path (fp32 bit-exact; int8 blocks import unrequantized —
byte-identical resident state).  ``"both"`` (default) keeps the
single-replica colocated shape; the router routes by role.

Observability: every request carries a **trace id**
(``submit(trace=...)``; minted when absent, propagated from the
``X-Veles-Trace`` header by the REST layer and router) and the
scheduler records its phase timeline — queue wait, admission (cold
vs prefix-warm, blocks claimed), each prefill chunk, batched
decode/verify boundaries (one span per boundary, per-request token
counts), preempt/resume, first token, retire — through
:mod:`veles_tpu.telemetry.reqtrace` into the JSONL event sink
(``trace_export --request <id>`` rebuilds the timeline).
:meth:`debug_requests` is the live in-flight table behind ``GET
/debug/requests``; per-class SLO good/bad counts and multi-window
burn rates (``root.common.slo.*``) ride ``stats.slo``.
"""

import collections
import concurrent.futures
import itertools
import os
import threading
import time

import numpy

from veles_tpu import faults
from veles_tpu.logger import Logger
from veles_tpu.telemetry import reqtrace
from veles_tpu.serving.engine import (
    first_tokens, paged_decode_step, slot_decode_step,
    verify_step_paged, verify_supported)
from veles_tpu.serving.kv_host import HostKVTier
from veles_tpu.serving.kv_slots import (
    PagedKVCache, SlotKVCache, paged_supported)
from veles_tpu.serving.metrics import ServingMetrics
from veles_tpu.serving.prefill import (
    chunked_supported, prefill, prefill_chunk, serving_supported,
    serving_window)
from veles_tpu.serving.prefix_cache import RadixPrefixCache
from veles_tpu.serving.draft import draft_supported
from veles_tpu.serving.spec import (
    NgramIndex, NgramProposer, accept_drafts)
from veles_tpu.serving.streams import TokenStream

#: priority classes, lowest to highest; ints in [0, 2] also accepted
PRIORITIES = {"low": 0, "normal": 1, "high": 2}
CLASS_NAMES = ("low", "normal", "high")
#: block-pressure shed trips at shed_block_factor x this fraction —
#: the LOW class sheds at half the documented budget, NORMAL at
#: exactly it (the pre-priority contract, unchanged), HIGH gets 1.5x
#: headroom so an overload sacrifices low-class work first
_SHED_FRAC = (0.5, 1.0, 1.5)
#: class-aware Retry-After seconds on a shed 503 (a shed low-class
#: client should back off longest — its work is what the overload
#: sacrifices first)
_RETRY_AFTER = (4, 2, 1)

#: process-unique default replica ids for metric labels (one per
#: scheduler built without an explicit fleet identity)
_SCHED_SEQ = itertools.count(1)


def resolve_priority(value):
    """Normalize a client priority (class name or int) to [0, 2];
    ``None`` means normal.  Raises ``ValueError`` on junk — a typo'd
    priority must be a client error, not silently-normal service."""
    if value is None:
        return PRIORITIES["normal"]
    if isinstance(value, str):
        try:
            return PRIORITIES[value.lower()]
        except KeyError:
            raise ValueError(
                "priority must be one of %s (or an int in [0, 2])"
                % "/".join(CLASS_NAMES))
    if isinstance(value, bool) or not isinstance(value, int):
        raise ValueError("priority must be a class name or int")
    if not 0 <= value <= 2:
        raise ValueError("priority int must be in [0, 2]")
    return value


class SchedulerError(Exception):
    """Base serving failure (maps to HTTP 500)."""
    http_status = 500


class QueueFullError(SchedulerError):
    """Admission control: queue-depth cap hit or block-pressure shed
    (HTTP 503; ``retry_after`` seeds the Retry-After header)."""
    http_status = 503
    retry_after = 1


class DrainingError(QueueFullError):
    """Admission closed for a graceful drain (HTTP 503) — the caller
    should retry against another replica."""
    retry_after = 5


class DeadlineExceededError(SchedulerError):
    """The request crossed its deadline — still queued
    (``tokens_generated == 0``) or mid-decode (HTTP 408; the partial
    count rides the error so clients know what they paid for)."""
    http_status = 408

    def __init__(self, message, tokens_generated=0):
        super(DeadlineExceededError, self).__init__(message)
        self.tokens_generated = int(tokens_generated)


class RequestCancelledError(SchedulerError):
    """The request was cancelled (client disconnect/abandon); its
    slot and KV blocks were released at the next boundary."""


class RoleMismatchError(SchedulerError):
    """The request phase does not match this replica's role (a
    decode submit on a prefill specialist or vice versa) — HTTP 409:
    the router should have dispatched it to the right pool."""
    http_status = 409


#: how long an unclaimed KV export survives (seconds) and how many
#: payload BYTES one prefill replica parks at once (the
#: ``kv_export_bytes`` knob's default) — the handoff is immediate in
#: a healthy fleet; these bound a crashed decode pool's leak.  A byte
#: budget replaces the old flat count-64 cap: records are whole
#: prompts of KV, so counting records let 64 long-prompt exports pin
#: unbounded host RAM while starving nothing
EXPORT_TTL = 120.0
EXPORT_BYTES = 256 << 20

#: cap on the per-replica cache-topology advertisement
#: (``prefix_digests`` in the metrics scrape) — breadth-first, so
#: the shallow, most shareable prefixes survive the cut
_DIGEST_MAX = 512


def _bucket(n, floor, cap):
    """Pad widths/counts to power-of-two buckets so the compiled
    executable count stays O(log) across arbitrary clients."""
    b = max(int(floor), 1)
    while b < n:
        b *= 2
    return min(b, cap)


def _serving_conf(name, default):
    from veles_tpu.config import root
    return root.common.serving.get(name, default)


def _metering_enabled():
    """``root.common.tsdb.metering`` — gates the per-tenant usage
    attribution (token counts at retire, KV-block-seconds and
    compute-seconds at step boundaries)."""
    from veles_tpu.config import root
    return bool(root.common.tsdb.get("metering", True))


class _Request(object):
    __slots__ = ("prompt", "steps", "temperature", "top_k",
                 "stop_token", "seed", "deadline", "future", "slot",
                 "generated", "cancelled", "preempts", "t_submit",
                 "t_admit", "t_first", "pf_seq", "pf_caches",
                 "pf_off", "pf_width", "pf_chunk", "pf_matched",
                 "prefix_handle", "priority", "sink", "trace",
                 "tenant", "export_only", "kv_import", "hid",
                 "draft_k", "accept_ema", "gram_ix")

    def __init__(self, prompt, steps, temperature, top_k, stop_token,
                 seed, deadline, priority=1, sink=None, trace=None,
                 tenant=None):
        self.prompt = prompt
        self.steps = steps
        self.temperature = temperature
        self.top_k = top_k
        self.stop_token = stop_token
        self.seed = seed
        self.deadline = deadline
        self.priority = int(priority)   # 0 low / 1 normal / 2 high
        self.sink = sink                # TokenStream._push (or None)
        self.trace = trace              # request trace id (reqtrace)
        self.tenant = tenant            # bounded tenant label (or None)
        self.future = concurrent.futures.Future()
        self.slot = None
        self.generated = []
        self.cancelled = False   # client gone — reap at next boundary
        self.preempts = 0        # times evicted (resume re-prefills)
        self.t_submit = time.monotonic()
        self.t_admit = None
        self.t_first = None
        # chunked-prefill progress (None while queued / one-shot);
        # pf_seq is the token sequence being prefilled — the prompt,
        # plus the generated prefix when resuming after a preemption
        self.pf_seq = None
        self.pf_caches = None
        self.pf_off = 0
        self.pf_width = 0
        self.pf_chunk = 0
        self.pf_matched = 0      # warm prefix blocks heading the slot
        self.prefix_handle = None  # pinned radix-cache match
        self.export_only = False  # prefill-role: stop after export
        self.kv_import = None     # decode-role: adopted export record
        # speculative-drafting state (spec mode): the last hidden
        # state the verify/decode lane returned for this request
        # (None until the first post-prefill step — the model drafter
        # falls back to n-gram there), the accept-rate-adaptive draft
        # length (set at admission), per-drafter accept-rate EMAs,
        # and the memoized trailing-ngram index
        self.hid = None
        self.draft_k = 0
        self.accept_ema = {}
        self.gram_ix = None

    def fail(self, error):
        """Set the future's exception unless a racing path (watchdog,
        cancel) beat us to it."""
        if not self.future.done():
            try:
                self.future.set_exception(error)
            except concurrent.futures.InvalidStateError:
                pass


class InferenceScheduler(Logger):
    """Continuous-batching decode service over a forward chain.

    ``max_slots`` — concurrent requests decoding per step;
    ``window`` — per-request length bound, ``prompt_len + steps <=
    window`` (default: the chain's positional table);
    ``max_queue`` — waiting-request cap beyond the slots (503 above);
    ``queue_timeout`` — default admission deadline in seconds (408
    for requests still queued past it);
    ``prefill_bucket`` — smallest compiled prefill width;
    ``kv`` / ``block_size`` / ``kv_blocks`` / ``prefill_chunk`` —
    paged-cache and chunked-prefill knobs (None defers to
    ``root.common.serving.*``; see the module docstring)."""

    def __init__(self, forwards, max_slots=4, window=None,
                 max_queue=32, queue_timeout=30.0, prefill_bucket=8,
                 kv=None, block_size=None, kv_blocks=None,
                 kv_dtype=None, prefill_chunk=None, warm_buckets=None,
                 request_timeout=None, watchdog=None,
                 shed_block_factor=None, spec=None, spec_k=None,
                 drafter=None, draft_head=None, draft_k_min=None,
                 draft_ema=None, prefix_cache=None, prefix_evict=None,
                 tp=None, role=None, replica_id=None,
                 kv_host_bytes=None, kv_export_bytes=None):
        super(InferenceScheduler, self).__init__()
        if not serving_supported(forwards):
            raise ValueError(
                "chain cannot serve through the slot scheduler (needs "
                "causal cacheable blocks with apply_prefill/"
                "apply_step_slots; see serving_supported)")
        window = window or serving_window(forwards)
        if not window or int(window) < 2:
            raise ValueError(
                "no usable decode window: pass window= (the chain has "
                "no learned positional table to derive it from)")
        self.forwards = forwards
        self.max_slots = int(max_slots)
        self.window = int(window)
        self.max_queue = int(max_queue)
        self.queue_timeout = float(queue_timeout)
        self.prefill_bucket = int(prefill_bucket)
        kv = kv or _serving_conf("kv", "paged")
        if kv not in ("paged", "dense"):
            raise ValueError("kv must be 'paged' or 'dense'")
        if kv == "paged" and not paged_supported(forwards):
            self.info("chain has no paged decode step; falling back "
                      "to the dense slot cache")
            kv = "dense"
        self.kv = kv
        self.block_size = int(
            block_size or _serving_conf("block_size", 16))
        if self.block_size < 1:
            raise ValueError("block_size must be >= 1")
        self.blocks_per_slot = -(-self.window // self.block_size)
        if kv_blocks is None:
            kv_blocks = _serving_conf("kv_blocks", None)
        self.kv_blocks = int(
            kv_blocks or self.max_slots * self.blocks_per_slot) \
            if self.kv == "paged" else 0
        #: KV pool storage dtype: "fp32" (compute-dtype pools; the
        #: parity baseline — token streams byte-identical to PR 5-11)
        #: or "int8" (per-row scales beside the block tables, ~half
        #: the bytes per cached token → ~2x streams per HBM budget;
        #: quality-gated, see serving/kv_quality.py).  Paged only.
        kv_dtype = kv_dtype or _serving_conf("kv_dtype", "fp32")
        if kv_dtype not in ("fp32", "int8"):
            raise ValueError("kv_dtype must be 'fp32' or 'int8'")
        if kv_dtype == "int8" and self.kv != "paged":
            self.info("kv_dtype='int8' needs the paged cache; "
                      "falling back to fp32")
            kv_dtype = "fp32"
        self.kv_dtype = kv_dtype
        chunk = prefill_chunk if prefill_chunk is not None \
            else _serving_conf("prefill_chunk", 64)
        chunk = int(chunk or 0)
        if chunk and not chunked_supported(forwards):
            self.info("chain cannot prefill in chunks; long prompts "
                      "will prefill one-shot")
            chunk = 0
        #: chunk widths ride compiled executables — power-of-two
        self.prefill_chunk = _bucket(chunk, 1, 1 << 30) if chunk else 0
        self.warm_buckets = bool(
            _serving_conf("warm_buckets", True)
            if warm_buckets is None else warm_buckets)
        #: whole-request deadline default in seconds (0/None = none
        #: beyond the legacy queue_timeout) — per-submit overridable
        self.request_timeout = float(
            _serving_conf("request_timeout", 120.0)
            if request_timeout is None else request_timeout)
        #: stuck-decode-loop threshold (0 disables the watchdog)
        self.watchdog = float(_serving_conf("watchdog", 300.0)
                              if watchdog is None else watchdog)
        #: shed new submits once the queue's committed block budget
        #: exceeds factor x kv_blocks (0 disables; paged only)
        self.shed_block_factor = float(
            _serving_conf("shed_block_factor", 4.0)
            if shed_block_factor is None else shed_block_factor)
        #: speculative decoding (serving/spec.py): draft up to spec_k
        #: tokens per slot by n-gram prompt lookup and score them in
        #: ONE batched verify pass — output streams stay bit-
        #: identical (greedy and per-seed sampling), accepted drafts
        #: are pure latency win.  Paged-KV only.
        spec = bool(_serving_conf("spec", False)
                    if spec is None else spec)
        self.spec_k = int(_serving_conf("spec_k", 4)
                          if spec_k is None else spec_k)
        if spec and self.spec_k < 1:
            raise ValueError("spec_k must be >= 1")
        if spec and (self.kv != "paged"
                     or not verify_supported(forwards)):
            self.info("chain/kv mode cannot run the paged verify "
                      "step; speculative decoding disabled")
            spec = False
        self.spec = spec
        self._proposer = NgramProposer(k=self.spec_k) if spec \
            else None
        #: draft source: "ngram" (prompt lookup, zero weights — the
        #: PR 9 baseline) or "model" (Medusa heads over the target's
        #: last hidden state, serving/draft.py — pass the trained
        #: head as ``draft_head``).  Arbitrated PER SLOT at runtime:
        #: the model head needs a hidden state (absent on the first
        #: step after prefill/resume) and per-drafter accept-rate
        #: EMAs pick whichever source earns its drafts; either way
        #: acceptance keeps streams bit-identical to spec-off
        drafter_ = str(_serving_conf("drafter", "ngram")
                       if drafter is None else drafter)
        if drafter_ not in ("ngram", "model"):
            raise ValueError("drafter must be 'ngram' or 'model'")
        if drafter_ == "model" and spec:
            if draft_head is None:
                self.info("drafter='model' needs a trained "
                          "draft_head; falling back to n-gram")
                drafter_ = "ngram"
            elif not draft_supported(forwards):
                self.info("chain has no hidden-state lane for the "
                          "model drafter; falling back to n-gram")
                drafter_ = "ngram"
        self.drafter = drafter_ if spec else "ngram"
        self._draft_head = draft_head \
            if spec and self.drafter == "model" else None
        if self._draft_head is not None:
            d, v = forwards[-1].weights.mem.shape
            if (self._draft_head.d_model,
                    self._draft_head.vocab) != (d, v):
                raise ValueError(
                    "draft_head sized (d=%d, vocab=%d) but the chain "
                    "serves (d=%d, vocab=%d)"
                    % (self._draft_head.d_model,
                       self._draft_head.vocab, d, v))
        #: accept-rate-adaptive draft length (spec mode): per-slot
        #: EMA of accepted/drafted with weight ``draft_ema`` shrinks
        #: the slot's draft k (halving, floor ``draft_k_min``) while
        #: acceptance is poor and grows it back toward spec_k while
        #: acceptance is high — the verify width then buckets to the
        #: power of two covering the longest live draft, so cold
        #: slots stop paying the full-k verify
        self.draft_k_min = int(_serving_conf("draft_k_min", 1)
                               if draft_k_min is None else draft_k_min)
        self.draft_k_min = max(1, min(self.draft_k_min, self.spec_k))
        self.draft_ema = float(_serving_conf("draft_ema", 0.5)
                               if draft_ema is None else draft_ema)
        if not 0.0 < self.draft_ema <= 1.0:
            raise ValueError("draft_ema must be in (0, 1]")
        self.draft_shrink = float(_serving_conf("draft_shrink", 0.5))
        self.draft_grow = float(_serving_conf("draft_grow", 0.8))
        #: cross-request radix prefix cache (serving/prefix_cache.py)
        #: — needs the paged cache, chunked prefill for the cold
        #: tail, and a power-of-two block size (the staging/chunk
        #: tilings assume it)
        pfx = bool(_serving_conf("prefix_cache", False)
                   if prefix_cache is None else prefix_cache)
        if pfx and (self.kv != "paged" or not self.prefill_chunk
                    or self.block_size & (self.block_size - 1)):
            self.info("prefix cache needs kv='paged', chunked "
                      "prefill and a power-of-two block size; "
                      "disabled")
            pfx = False
        self.prefix_cache = pfx
        self.prefix_evict = bool(
            _serving_conf("prefix_evict", True)
            if prefix_evict is None else prefix_evict)
        #: host-RAM overflow tier byte budget (serving/kv_host.py):
        #: prefix-cache evictions demote block contents to host RAM
        #: instead of dropping them, and matching admissions promote
        #: them back.  0 disables (the tier-1 baseline); needs the
        #: prefix cache (the tier is keyed by its token paths)
        hb = int(_serving_conf("kv_host_bytes", 0)
                 if kv_host_bytes is None else kv_host_bytes or 0)
        if hb and not pfx:
            self.info("kv_host_bytes needs the prefix cache; host "
                      "tier disabled")
            hb = 0
        self.kv_host_bytes = hb
        #: parked-export byte budget (replaces the flat count cap):
        #: oldest unclaimed records pay when a new park would
        #: overflow it, counted as expiries
        self.kv_export_bytes = int(
            _serving_conf("kv_export_bytes", EXPORT_BYTES)
            if kv_export_bytes is None else kv_export_bytes
            or EXPORT_BYTES)
        #: tensor-parallel mesh size (0 = off): shards the jitted
        #: steps over a {"tp": N} mesh — Megatron weight splits +
        #: head-wise paged pools, per-chip kv_blocks HBM / N
        #: (serving/tp.py; module docstring).  Needs the paged cache,
        #: N devices, and a chain whose blocks declare tp layouts.
        tp = int(_serving_conf("tp", 0) if tp is None else tp or 0)
        if tp == 1:
            tp = 0
        self.tp_ = None
        if tp:
            from veles_tpu.serving.tp import ServingTP, tp_supported
            import jax
            if self.kv != "paged":
                self.info("tp needs the paged cache; serving "
                          "unsharded")
                tp = 0
            elif len(jax.devices()) < tp:
                self.info("tp=%d needs %d devices, found %d; serving "
                          "unsharded", tp, tp, len(jax.devices()))
                tp = 0
            elif not tp_supported(forwards, tp):
                self.info("chain does not divide over tp=%d (heads/"
                          "d_model/hidden divisibility, or a MoE/"
                          "int8-weight block); serving unsharded", tp)
                tp = 0
            else:
                self.tp_ = ServingTP(tp)
        self.tp = tp
        #: disaggregation role (module docstring): "prefill" accepts
        #: only submit_prefill and parks KV exports; "decode" adopts
        #: them via submit_imported; "both" is the colocated default
        role = str(role or _serving_conf("role", "both")).lower()
        if role not in ("both", "prefill", "decode"):
            raise ValueError(
                "role must be 'prefill', 'decode' or 'both'")
        if role == "prefill" and self.kv != "paged":
            raise ValueError("role='prefill' needs the paged cache "
                             "(block export is block-granular)")
        self.role = role
        #: identity for the per-replica metric labels (satellite of
        #: the last-scheduler-wins gauge fix): the fleet's replica id
        #: when the REST layer passes one, else a process-unique name
        self.replica_id = str(replica_id) if replica_id \
            else "sched%d" % next(_SCHED_SEQ)
        self.stats = ServingMetrics(replica=self.replica_id)
        self._exports = {}           # handle -> export record (lock)
        self._exports_bytes = 0      # parked payload bytes (lock)
        self._exports_claimed = {}   # handle -> fetch time (lock) —
        #                              what tells a double-fetch race
        #                              (409) from a junk handle (404)
        #: per-request tracing (telemetry/reqtrace.py), read ONCE at
        #: construction — the per-boundary gate must be an attribute
        #: test, not a config-tree walk
        self._tron = reqtrace.enabled()
        #: per-tenant metering gate (root.common.tsdb.metering), read
        #: ONCE for the same reason — the step boundary is the hot
        #: path the overhead soak holds to <5%
        self._metering = _metering_enabled()
        self._queue = collections.deque()
        self._active = {}            # slot -> _Request (decoding)
        self._prefilling = []        # admitted, mid-chunked-prefill
        self._admitting = []         # popped from queue, prefill in
        #                              progress this very iteration —
        #                              cancel() must still see them
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._closed = False
        self._draining = False
        self._drained = threading.Event()
        self._preempts_owed = []     # eviction demands (class bound
        #                              per entry; None = any victim)
        self._aux = collections.deque()  # embed/score jobs (loop-run)
        self._prefix_jobs = collections.deque()  # tiered-KV prefix
        #                              export/import jobs (loop-run,
        #                              one per boundary like _aux)
        self._queued_blocks = 0      # block budget committed in-queue
        self._beat = None            # loop-iteration heartbeat stamp
        self._working = False        # loop mid-iteration (not parked)
        self._tripped_beat = None    # last beat the watchdog fired on
        self._thread = None
        self._watchdog_thread = None
        self._ready = threading.Event()
        self.cache_ = None           # set by the loop thread
        self.prefix_ = None          # radix cache (loop thread too)
        #: host KV tier — constructed HERE (no device dependencies)
        #: so the reference is immutable across threads; only the
        #: loop thread mutates its contents
        self.host_ = HostKVTier(self.kv_host_bytes,
                                self.block_size) \
            if self.kv_host_bytes > 0 else None

    # -- client side ----------------------------------------------------

    def start(self):
        """Warm the device params (single-threaded — Array.devmem's
        lazy upload is not re-entrant), start the decode loop and
        block until it is READY — cache built and the paged-step
        bucket ladder compiled — so traffic never eats warmup
        compiles as decode stalls."""
        with self._lock:  # two racing start()s must not spawn two loops
            if self._thread is not None:
                started = True
            else:
                started = False
                self._thread = threading.Thread(
                    target=self._loop, daemon=True,
                    name="serving-scheduler")
        if started:
            self._ready.wait(600)
            return self
        try:
            for u in self.forwards:
                for arr in u.param_arrays().values():
                    arr.devmem
            self._thread.start()
        except BaseException:
            with self._lock:  # release the claim so start() can retry
                self._thread = None
            raise
        self._ready.wait(600)
        if self.watchdog > 0 and self._watchdog_thread is None:
            self._watchdog_thread = threading.Thread(
                target=self._watchdog_loop, daemon=True,
                name="serving-watchdog")
            self._watchdog_thread.start()
        # flight-recorder / debug surface: a hang dump can enumerate
        # this scheduler's live requests (weakly held — close() needs
        # no deregistration)
        reqtrace.register("scheduler", self)
        return self

    def submit(self, prompt, steps, temperature=0.0, top_k=0,
               seed=None, stop_token=None, timeout=None,
               priority=None, stream=False, trace=None,
               resume_tokens=None, tenant=None):
        """Queue one sequence for decoding; returns a Future whose
        result is the full token list (prompt + generated, ending at
        the first generated stop token if one fired).  ``timeout``
        overrides the whole-request deadline (default
        ``request_timeout``; it covers queueing AND decoding — expiry
        mid-decode frees the slot/blocks and fails the future with
        :class:`DeadlineExceededError`).

        ``resume_tokens`` adopts an already-generated prefix — the
        mid-stream-failover lane: the request admits with
        ``generated`` pre-populated, re-prefills prompt + prefix
        through the chunked path (exactly the preempt→resume
        machinery) and samples its next token at draw counter
        ``len(resume_tokens)``, so the continued stream is
        bit-identical to an uninterrupted run of the same
        prompt/seed/params (fp32; int8 pools continue within the
        documented quantization-noise contract).  ``steps`` stays
        the request's TOTAL generation budget — the resumed prefix
        counts against it — and a stream sink receives only the
        NEWLY drawn tokens.

        ``priority`` ("low"/"normal"/"high" or 0–2, default normal)
        sets the request's QoS class: admission order, shed
        threshold/Retry-After, and preemption victimhood are all
        class-aware (module docstring).  ``stream=True`` returns a
        :class:`~veles_tpu.serving.streams.TokenStream` (its
        ``.future`` is the same future the plain path returns)
        yielding tokens as they are accepted.  ``trace`` attaches a
        request trace id (the ``X-Veles-Trace`` propagation value —
        sanitized here; None mints a fresh one): every phase span the
        scheduler records for this request carries it, which is what
        ``trace_export --request`` merges on.

        Raises ``ValueError`` on malformed requests (client errors),
        :class:`QueueFullError` when admission control rejects (queue
        depth, block-pressure shed, or :class:`DrainingError` once a
        drain began)."""
        if self.role == "prefill":
            raise RoleMismatchError(
                "prefill-role replica serves POST /serving/prefill "
                "only — decode requests belong on the decode pool")
        prio = resolve_priority(priority)
        prompt = [int(t) for t in prompt]
        steps = int(steps)
        if not prompt:
            raise ValueError("prompt must be non-empty")
        if steps < 1:
            raise ValueError("steps must be >= 1")
        resume = [int(t) for t in resume_tokens] \
            if resume_tokens else []
        if len(resume) >= steps:
            raise ValueError(
                "resume_tokens already cover the %d-step budget "
                "(%d resumed) — nothing left to generate"
                % (steps, len(resume)))
        if len(prompt) + steps > self.window:
            raise ValueError(
                "prompt_len + steps = %d exceeds the serving window "
                "(%d)" % (len(prompt) + steps, self.window))
        if self.kv == "paged":
            need = -(-(len(prompt) + steps) // self.block_size)
            if need > self.kv_blocks:
                raise ValueError(
                    "request needs %d KV blocks > pool capacity %d "
                    "(kv_blocks)" % (need, self.kv_blocks))
        temperature = float(temperature or 0.0)
        top_k = int(top_k or 0)
        if top_k and not temperature:
            raise ValueError(
                "top_k only applies to sampling — set temperature > 0")
        if seed is None:
            # unpinned sampling must draw fresh tokens per request
            seed = int.from_bytes(os.urandom(4), "little")
        ttl = float(timeout or self.request_timeout
                    or self.queue_timeout or 0)
        trace = reqtrace.ensure_trace_id(trace)
        ts = TokenStream(prompt) if stream else None
        if ts is not None:
            ts.trace = trace
        req = _Request(
            prompt, steps, temperature, top_k,
            int(stop_token) if stop_token is not None else None,
            int(seed) & 0xFFFFFFFF,
            time.monotonic() + ttl if ttl > 0 else None,
            priority=prio, sink=ts._push if ts is not None else None,
            trace=trace,
            tenant=str(tenant) if tenant is not None else None)
        if resume:
            # the failover-resume lane rides the preempt→resume
            # machinery: the adopted prefix re-prefills with the
            # prompt and the next draw folds counter len(resume) —
            # the sink sees only tokens drawn HERE
            req.generated = resume
        self._admission_enqueue(req)
        if ts is not None:
            ts._bind(self, req.future)
            return ts
        return req.future

    def _admission_enqueue(self, req):
        """Admission control + enqueue for one built request — the
        shared tail of :meth:`submit`, :meth:`submit_prefill` and
        :meth:`submit_imported` (drain/queue-cap/block-pressure
        checks under the wake lock)."""
        prio = req.priority
        need = self._blocks_for(req)
        cls = CLASS_NAMES[prio]
        with self._wake:
            if self._closed:
                raise SchedulerError("scheduler is closed")
            if self._draining:
                # rolling restart: this replica finishes what it has
                # and takes nothing new — callers retry elsewhere
                self.stats.record_reject(len(self._queue))
                raise DrainingError("scheduler is draining")
            if len(self._queue) >= self.max_queue \
                    and not self._evict_queued_locked(prio):
                self.stats.record_reject(len(self._queue))
                err = QueueFullError(
                    "serving queue full (%d waiting)"
                    % len(self._queue))
                err.retry_after = _RETRY_AFTER[prio]
                raise err
            if self.kv == "paged" and self.shed_block_factor > 0 \
                    and self._queued_blocks + need \
                    > self.shed_block_factor * _SHED_FRAC[prio] \
                    * self.kv_blocks:
                # block-pressure shed, LOW class first: each class
                # trips at its own fraction of the factor, so as
                # pressure builds the overload sacrifices low-class
                # work while high-class admission still has headroom
                # — and a shed low client backs off longer
                self.stats.record_shed(self._queued_blocks, cls=cls,
                                       trace=req.trace)
                err = QueueFullError(
                    "overloaded: %d KV blocks committed in-queue "
                    "(pool %d, %s-class shed at factor %.1f)"
                    % (self._queued_blocks, self.kv_blocks, cls,
                       self.shed_block_factor * _SHED_FRAC[prio]))
                err.retry_after = _RETRY_AFTER[prio]
                raise err
            self.stats.record_submit(cls=cls)
            self._enqueue_locked(req)
            self._queued_blocks += need
            self._wake.notify()

    def submit_prefill(self, prompt, seed=None, timeout=None,
                       priority=None, trace=None):
        """Queue one prompt for PREFILL-ONLY service (the
        disaggregated fleet's prefill half; roles "prefill"/"both"):
        the prompt rides the normal admission + chunked-prefill path,
        but instead of decoding, the finished KV blocks are gathered
        RAW (scales included under int8) together with the
        last-position logits and parked under a handle for ``GET
        /serving/kv_export/<handle>``.  The returned future resolves
        to ``{"handle", "prompt_tokens", "blocks"}``.  No sampler
        parameters here — sampling is the decode replica's business
        (it draws from the exported logits with ITS
        temperature/seed, which is what keeps the handed-off stream
        identical to the colocated one)."""
        if self.role == "decode":
            raise RoleMismatchError(
                "decode-role replica imports KV (POST "
                "/serving/kv_import) — prefill belongs on the "
                "prefill pool")
        if self.kv != "paged":
            raise ValueError("prefill export needs the paged cache")
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("prompt must be non-empty")
        if len(prompt) > self.window:
            raise ValueError(
                "prompt of %d tokens exceeds the serving window (%d)"
                % (len(prompt), self.window))
        prio = resolve_priority(priority)
        ttl = float(timeout or self.request_timeout
                    or self.queue_timeout or 0)
        trace = reqtrace.ensure_trace_id(trace)
        if seed is None:
            seed = int.from_bytes(os.urandom(4), "little")
        req = _Request(
            prompt, 1, 0.0, 0, None, int(seed) & 0xFFFFFFFF,
            time.monotonic() + ttl if ttl > 0 else None,
            priority=prio, trace=trace)
        req.export_only = True
        self._admission_enqueue(req)
        return req.future

    def kv_export(self, handle):
        """Claim one parked export record (one-shot — the fetch
        consumes it), or None when the handle is unknown/expired/
        already fetched (:meth:`kv_export_status` tells those
        apart).  The record is the host-side numpy form;
        ``serving/disagg.encode_export`` is the wire envelope."""
        now = time.monotonic()
        with self._lock:
            self._sweep_exports_locked(now)
            rec = self._exports.pop(str(handle), None)
            if rec is not None:
                self._exports_bytes -= rec.get("bytes", 0)
                self._exports_claimed[str(handle)] = now
                self.stats.record_kv_export_fetched()
                self.stats.set_kv_exports_pending(len(self._exports))
            return rec

    def kv_export_status(self, handle):
        """One-shot-fetch disambiguation for the REST layer:
        ``"pending"`` (parked, fetchable), ``"fetched"`` (already
        claimed — a second fetch is a 409 race, not a missing
        record) or ``"unknown"`` (never parked, or expired and
        swept)."""
        with self._lock:
            if str(handle) in self._exports:
                return "pending"
            if str(handle) in self._exports_claimed:
                return "fetched"
            return "unknown"

    def _sweep_exports_locked(self, now=None):
        """TTL housekeeping over the parked export records (caller
        holds the lock): GC expired records, prune the claimed-handle
        memory, and keep the pending gauge honest.  Returns how many
        records expired.  Piggybacked on the decode loop (idle
        replicas sweep on a 1 s condition-wait timeout), so a
        crashed decode pool's unfetched handoffs stop rotting until
        the cap."""
        now = time.monotonic() if now is None else now
        stale = [h for h, r in self._exports.items()
                 if now - r["t"] > EXPORT_TTL]
        for h in stale:
            self._exports_bytes -= self._exports[h].get("bytes", 0)
            del self._exports[h]
        if stale:
            self.stats.record_kv_export_expired(len(stale))
            self.stats.set_kv_exports_pending(len(self._exports))
        dead = [h for h, t in self._exports_claimed.items()
                if now - t > 2 * EXPORT_TTL]
        for h in dead:
            del self._exports_claimed[h]
        return len(stale)

    def submit_imported(self, export, steps, temperature=0.0,
                        top_k=0, seed=None, stop_token=None,
                        timeout=None, priority=None, stream=False,
                        trace=None):
        """Adopt a prefill replica's export record (the decoded form
        of ``GET /serving/kv_export/<handle>``; roles
        "decode"/"both") and decode ``steps`` tokens: admission
        claims the full prompt+steps block budget, the exported
        blocks scatter straight into the slot's table (no prefill
        pass at all — the decode replica's TTFT is one block
        scatter), and the first token samples from the exported
        logits with the caller's sampler settings — the stream is
        identical to a colocated ``submit`` of the same prompt
        (fp32 bit-exact; int8 byte-identical resident KV).  Raises
        ``ValueError`` on a record that doesn't match this replica's
        pool layout (kv_dtype / block_size / window)."""
        if self.role == "prefill":
            raise RoleMismatchError(
                "prefill-role replica exports KV — imports belong "
                "on the decode pool")
        if self.kv != "paged":
            raise ValueError("kv import needs the paged cache")
        prompt = [int(t) for t in export.get("prompt", ())]
        steps = int(steps)
        if not prompt or int(export.get("length", -1)) != len(prompt):
            raise ValueError("export record prompt/length mismatch")
        if steps < 1:
            raise ValueError("steps must be >= 1")
        if str(export.get("kv_dtype")) != self.kv_dtype:
            raise ValueError(
                "export kv_dtype %r != this replica's %r — "
                "disaggregated pools must share a storage dtype"
                % (export.get("kv_dtype"), self.kv_dtype))
        if int(export.get("block_size", 0)) != self.block_size:
            raise ValueError(
                "export block_size %s != this replica's %d"
                % (export.get("block_size"), self.block_size))
        if len(prompt) + steps > self.window:
            raise ValueError(
                "prompt_len + steps = %d exceeds the serving window "
                "(%d)" % (len(prompt) + steps, self.window))
        need = -(-(len(prompt) + steps) // self.block_size)
        if need > self.kv_blocks:
            raise ValueError(
                "request needs %d KV blocks > pool capacity %d "
                "(kv_blocks)" % (need, self.kv_blocks))
        temperature = float(temperature or 0.0)
        top_k = int(top_k or 0)
        if top_k and not temperature:
            raise ValueError(
                "top_k only applies to sampling — set temperature > 0")
        if seed is None:
            seed = int.from_bytes(os.urandom(4), "little")
        prio = resolve_priority(priority)
        ttl = float(timeout or self.request_timeout
                    or self.queue_timeout or 0)
        trace = reqtrace.ensure_trace_id(trace)
        ts = TokenStream(prompt) if stream else None
        if ts is not None:
            ts.trace = trace
        req = _Request(
            prompt, steps, temperature, top_k,
            int(stop_token) if stop_token is not None else None,
            int(seed) & 0xFFFFFFFF,
            time.monotonic() + ttl if ttl > 0 else None,
            priority=prio, sink=ts._push if ts is not None else None,
            trace=trace)
        req.kv_import = export
        self._admission_enqueue(req)
        if ts is not None:
            ts._bind(self, req.future)
            return ts
        return req.future

    def _submit_prefix_job(self, kind, payload):
        if self.kv != "paged" or not self.prefix_cache:
            raise ValueError(
                "prefix %s needs the paged cache with the prefix "
                "cache enabled" % kind)
        fut = concurrent.futures.Future()
        with self._wake:
            if self._closed:
                raise SchedulerError("scheduler is closed")
            if len(self._prefix_jobs) >= self.max_queue:
                raise QueueFullError(
                    "prefix-transfer queue full (%d waiting)"
                    % len(self._prefix_jobs))
            self._prefix_jobs.append((kind, payload, fut))
            self._wake.notify()
        return fut

    def submit_prefix_export(self, tokens):
        """Queue a peer-prefix read (the fleet-wide prefix store's
        GET half): the future resolves to an export-shaped record —
        no logits, prompt truncated to the covered prefix — holding
        the RAW blocks of the longest resident prefix of ``tokens``
        across BOTH tiers (device trie, then its host-tier
        extension), or None when nothing is resident.  Works on a
        draining replica: reads don't extend its in-flight set,
        and a drained peer's warm state is exactly what's worth
        rescuing."""
        tokens = [int(t) for t in tokens]
        if not tokens:
            raise ValueError("tokens must be non-empty")
        return self._submit_prefix_job("export", tokens)

    def submit_prefix_import(self, record):
        """Queue a peer-prefix adoption (the router ships a
        :meth:`submit_prefix_export` record from the replica that
        had it): new chunks take freshly claimed device blocks and
        join the trie, so the triggering request — and every later
        one — admits warm here.  The future resolves to ``{"blocks":
        adopted}``.  Raises ``ValueError`` on a record that doesn't
        match this replica's pool layout."""
        if str(record.get("kv_dtype")) != self.kv_dtype:
            raise ValueError(
                "prefix record kv_dtype %r != this replica's %r"
                % (record.get("kv_dtype"), self.kv_dtype))
        if int(record.get("block_size", 0)) != self.block_size:
            raise ValueError(
                "prefix record block_size %s != this replica's %d"
                % (record.get("block_size"), self.block_size))
        prompt = [int(t) for t in record.get("prompt", ())]
        if not prompt or int(record.get("length", -1)) != len(prompt):
            raise ValueError("prefix record prompt/length mismatch")
        if len(prompt) % self.block_size:
            raise ValueError("prefix record must be block-aligned")
        return self._submit_prefix_job("import", record)

    def _enqueue_locked(self, req, front=False):
        """Insert one request into the class-ordered queue (highest
        class first, FIFO within a class); ``front=True`` requeues a
        preempted victim at the head of ITS class so it resumes
        before later same-class arrivals."""
        q = self._queue
        if front:
            i = 0
            while i < len(q) and q[i].priority > req.priority:
                i += 1
        else:
            i = len(q)
            while i > 0 and q[i - 1].priority < req.priority:
                i -= 1
        q.insert(i, req)

    def _evict_queued_locked(self, prio):
        """Depth-cap relief for a higher-class arrival: shed the
        YOUNGEST queued strictly-lower-class request (it loses the
        least wait) and report whether a seat opened.  The victim
        gets the same structured 503 + its class's Retry-After a
        front-door shed would have given it."""
        victim = None
        for req in reversed(self._queue):
            if req.priority < prio:
                victim = req
                break
        if victim is None:
            return False
        self._queue.remove(victim)
        self._queued_blocks -= self._blocks_for(victim)
        vcls = CLASS_NAMES[victim.priority]
        self.stats.record_shed(self._queued_blocks, cls=vcls,
                               trace=victim.trace)
        err = QueueFullError(
            "shed while queued: a higher-priority request took the "
            "last queue seat")
        err.retry_after = _RETRY_AFTER[victim.priority]
        victim.fail(err)
        return True

    def _budget_tokens(self, req):
        """The token span a request's block budget must cover: prompt
        + decode steps, or just the prompt for a prefill-export
        request (it never decodes here — the decode replica claims
        the steps' blocks on ITS pool)."""
        if req.export_only:
            return len(req.prompt)
        return len(req.prompt) + req.steps

    def _blocks_for(self, req):
        """The paged block budget a request commits (0 when dense)."""
        if self.kv != "paged":
            return 0
        return -(-self._budget_tokens(req) // self.block_size)

    def cancel(self, future, reason="cancelled by client"):
        """Cancel the request behind ``future`` (client disconnected
        or gave up): a queued request fails immediately; an in-flight
        one is reaped at the next chunk/decode boundary, returning its
        slot and KV blocks to the pool.  Returns True when the future
        belonged to this scheduler and was still unfinished."""
        victim = None
        with self._wake:
            for req in self._queue:
                if req.future is future:
                    self._queue.remove(req)
                    self._queued_blocks -= self._blocks_for(req)
                    victim = req
                    break
            else:
                for req in list(self._prefilling) \
                        + list(self._active.values()) \
                        + list(self._admitting):
                    if req.future is future:
                        req.cancelled = True
                        victim = req
                        self._wake.notify()
                        break
        if victim is None:
            return False
        if victim.slot is None and not victim.cancelled:
            # was queued: no device state to release — fail right here
            victim.fail(RequestCancelledError(reason))
            self.stats.record_cancel(len(victim.generated),
                                     trace=victim.trace)
        return True

    def request_preempt(self, n=1, below=None):
        """Ask the loop to evict ``n`` active requests at the next
        decode boundary: victim selection takes the LOWEST priority
        class first, youngest within it (it loses the least
        re-prefill work).  ``below`` bounds victimhood to requests of
        priority strictly under it (a demand with no qualifying
        victim is dropped); ``None`` preempts from any class.  Each
        victim's blocks return to the pool, its generated prefix is
        kept, and it requeues at the front of its class to resume via
        re-prefill — the mechanism priority scheduling drives."""
        with self._wake:
            self._preempts_owed.extend(
                [None if below is None else int(below)] * int(n))
            self._wake.notify()

    def submit_embed(self, rows):
        """Queue ONE batched embedding job (``/v1/embeddings``):
        ``rows`` are non-empty token lists; the future resolves to a
        list of pooled unit-norm vectors (see
        :func:`serving.openai_api.embed_pool`).  The job runs on the
        decode loop BETWEEN decode boundaries — embeddings share the
        engine without breaking the one-jax-thread invariant."""
        return self._submit_aux("embed", rows)

    def submit_score(self, rows):
        """Queue ONE batched classifier-scoring job
        (``/v1/classify``): the future resolves to per-row class
        log-probabilities from the full chain's last-position
        logits."""
        return self._submit_aux("score", rows)

    def _submit_aux(self, kind, rows):
        rows = [[int(t) for t in r] for r in rows]
        if not rows or any(not r for r in rows):
            raise ValueError("input must be non-empty token rows")
        widest = max(len(r) for r in rows)
        if widest > self.window:
            raise ValueError(
                "input row of %d tokens exceeds the serving window "
                "(%d)" % (widest, self.window))
        if kind == "embed":
            from veles_tpu.serving.openai_api import embed_supported
            if not embed_supported(self.forwards):
                raise ValueError("chain cannot serve embeddings")
        fut = concurrent.futures.Future()
        with self._wake:
            if self._closed:
                raise SchedulerError("scheduler is closed")
            if self._draining:
                raise DrainingError("scheduler is draining")
            if len(self._aux) >= self.max_queue:
                self.stats.record_reject(len(self._aux))
                raise QueueFullError(
                    "aux queue full (%d waiting)" % len(self._aux))
            self._aux.append((kind, rows, fut))
            self._wake.notify()
        return fut

    def _aux_tick(self):
        """Run ONE queued embed/score job (one jitted pass) at this
        boundary — like a prefill chunk, it delays in-flight decode by
        a single bounded pass, not by the whole aux backlog."""
        with self._lock:
            if not self._aux:
                return
            kind, rows, fut = self._aux.popleft()
        if fut.done():   # consumer already gave up
            return
        from veles_tpu.serving.openai_api import (
            pooled_embeddings, score_rows)
        try:
            faults.fire("serving.scheduler.aux")
            if kind == "embed":
                out = pooled_embeddings(self.forwards, rows,
                                        self.window)
            else:
                out = score_rows(self.forwards, rows, self.window)
        except Exception as e:
            fut.set_exception(
                e if isinstance(e, SchedulerError)
                else SchedulerError(repr(e)))
            return
        try:
            fut.set_result(out)
        except concurrent.futures.InvalidStateError:
            pass

    def _prefix_tick(self, cache):
        """Run ONE queued prefix export/import job at this boundary —
        the same decode-stall bound as a prefill chunk or an aux
        pass."""
        with self._lock:
            if not self._prefix_jobs:
                return
            kind, payload, fut = self._prefix_jobs.popleft()
        if fut.done():   # consumer already gave up
            return
        try:
            if kind == "export":
                out = self._prefix_export_job(cache, payload)
            else:
                out = self._prefix_import_job(cache, payload)
        except Exception as e:
            fut.set_exception(
                e if isinstance(e, SchedulerError)
                else SchedulerError(repr(e)))
            return
        try:
            fut.set_result(out)
        except concurrent.futures.InvalidStateError:
            pass

    def _prefix_export_job(self, cache, tokens):
        """Gather the longest resident prefix of ``tokens`` — the
        device trie walk, then its host-tier extension (already host
        numpy, the gather is free) — into an export-shaped record."""
        if self.prefix_ is None:
            return None
        bs = self.block_size
        ids = self.prefix_.resident_prefix(tokens)
        layers = cache.export_blocks(ids) if ids else None
        if self.host_ is not None:
            entries = self.host_.match(tokens, len(ids))
            for e in entries:
                if layers is None:
                    layers = {i: {nm: a.mem.copy()
                                  for nm, a in row.items()}
                              for i, row in e.layers.items()}
                    continue
                if set(e.layers) != set(layers):
                    break  # defensive: mismatched chain shape
                layers = {i: {nm: numpy.concatenate(
                    [layers[i][nm], e.layers[i][nm].mem])
                    for nm in layers[i]} for i in layers}
        if layers is None:
            return None
        blocks = next(iter(next(iter(
            layers.values())).values())).shape[0]
        covered = blocks * bs
        from veles_tpu.serving.disagg import mint_handle
        return {
            "handle": mint_handle(),
            "prompt": [int(t) for t in tokens[:covered]],
            "length": covered,
            "kv_dtype": self.kv_dtype,
            "block_size": bs,
            "layers": layers,
        }

    def _prefix_import_job(self, cache, record):
        """Adopt a peer's prefix record: chunks already resident
        keep their incumbents; the new consecutive extension
        scatters into freshly claimed blocks and joins the trie.
        Fires the promote fault point — a peer import IS a
        promotion into the device tier, just from a remote source."""
        pfx = self.prefix_
        if pfx is None:
            raise SchedulerError("no prefix cache on this replica")
        bs = self.block_size
        tokens = record["prompt"]
        total = int(record["length"]) // bs
        dev = pfx.resident_prefix(tokens)
        n_new = total - len(dev)
        ids = None
        while n_new > 0:
            ids = cache.take_free_blocks(n_new)
            if ids is not None:
                break
            n_new -= 1  # adopt the longest extension that fits
        if not n_new or ids is None:
            return {"blocks": 0}
        try:
            faults.fire("scheduler.kv.promote")
            sliced = {i: {nm: a[len(dev):len(dev) + n_new]
                          for nm, a in layer.items()}
                      for i, layer in record["layers"].items()}
            cache.import_blocks(ids, sliced)
        except Exception:
            cache.reclaim(ids)
            raise
        covered = (len(dev) + n_new) * bs
        _, rejected = pfx.insert(
            [int(t) for t in tokens[:covered]], dev + ids)
        if rejected:
            cache.reclaim(rejected)
        self._sync_prefix_gauges()
        return {"blocks": n_new}

    def drain(self, timeout=None):
        """Begin a graceful drain: admission closes (submits raise
        :class:`DrainingError` — 503 + Retry-After material), every
        queued and in-flight request runs to completion, then the
        ``drained`` event sets.  With ``timeout`` the call blocks for
        the drain to finish and returns whether it did; otherwise it
        returns immediately."""
        with self._wake:
            first = not self._draining
            self._draining = True
            if not (self._queue or self._active or self._prefilling
                    or self._aux):
                self._drained.set()
            self._wake.notify()
        if first:
            self.stats.record_drain()
            self.info("draining: admission closed, %d in flight",
                      self.in_flight)
        if timeout is not None:
            return self._drained.wait(timeout)
        return self._drained.is_set()

    @property
    def draining(self):
        return self._draining

    @property
    def drained(self):
        return self._drained.is_set()

    @property
    def in_flight(self):
        """Requests the scheduler still owes an answer (queued +
        prefilling + decoding)."""
        with self._lock:
            return len(self._queue) + len(self._prefilling) \
                + len(self._active) + len(self._admitting) \
                + len(self._aux)

    def _kv_snapshot(self):
        out = {"kv_mode": self.kv,
               "prefill_chunk": self.prefill_chunk,
               "prefilling": len(self._prefilling),
               "tp": self.tp,
               "role": self.role,
               "replica": self.replica_id,
               "kv_exports_pending": len(self._exports)}
        cache = self.cache_
        if self.kv == "paged":
            out["kv_dtype"] = self.kv_dtype
            out["kv_bytes_per_token"] = \
                cache.bytes_per_token() if cache is not None else None
            out["kv_block_size"] = self.block_size
            out["kv_blocks_total"] = self.kv_blocks
            # the loop thread owns the free lists; these reads are
            # monitoring-grade (len() is atomic enough for a gauge)
            out["kv_blocks_used"] = \
                cache.used_blocks if cache is not None else 0
            out["kv_blocks_free"] = \
                cache.free_blocks if cache is not None \
                else self.kv_blocks
        out["spec"] = self.spec
        out["spec_k"] = self.spec_k if self.spec else 0
        out["drafter"] = self.drafter if self.spec else None
        out["draft_k_min"] = self.draft_k_min if self.spec else 0
        pfx = self.prefix_
        out["prefix_cache"] = pfx is not None
        if pfx is not None:  # loop-owned; monitoring-grade reads
            total = pfx.hits + pfx.misses
            out["prefix_cache_hits"] = pfx.hits
            out["prefix_cache_misses"] = pfx.misses
            out["prefix_cache_evictions"] = pfx.evictions
            out["prefix_cache_hit_blocks"] = pfx.hit_blocks
            out["prefix_cache_blocks_resident"] = pfx.resident
            out["prefix_cache_blocks_shared"] = pfx.shared_blocks()
            out["prefix_cache_hit_rate"] = \
                round(pfx.hits / total, 4) if total else None
            # the cache-topology advertisement: rolling path digests
            # of every resident prefix, BOTH tiers (a host-resident
            # prefix is promotable, so it is routable warmth too).
            # The router matches prompts against these to route on
            # who actually holds the longest prefix
            digs = pfx.path_digests(_DIGEST_MAX)
            host = self.host_
            if host is not None:
                digs.extend(host.digests()[:max(
                    0, _DIGEST_MAX - len(digs))])
                out["kv_host_blocks"] = host.blocks
                out["kv_host_bytes"] = host.bytes
                out["kv_host_promotions"] = host.promotions
                out["kv_host_demotions"] = host.demotions
                out["kv_host_evictions"] = host.evictions
            out["prefix_digests"] = digs
        return out

    def metrics(self):
        with self._lock:
            depth, active = len(self._queue), len(self._active)
            draining = self._draining
            queued_blocks = self._queued_blocks
        snap = self.stats.snapshot(queue_depth=depth,
                                   active_slots=active,
                                   max_slots=self.max_slots,
                                   kv=self._kv_snapshot())
        snap["window"] = self.window
        snap["draining"] = draining
        snap["drained"] = self._drained.is_set()
        snap["queued_kv_blocks"] = queued_blocks
        snap["tenants"] = self.stats.tenant_usage_snapshot()
        return snap

    def debug_requests(self):
        """Live in-flight request table (``GET /debug/requests`` and
        the flight-recorder bundle): one row per request the
        scheduler still owes an answer, with its trace id, phase,
        class, age and the KV blocks it holds.  Monitoring-grade
        reads — the loop thread owns the cache tables, so block
        counts are len()/int-read consistent, not transactional."""
        now = time.monotonic()
        cache = self.cache_
        with self._lock:
            rows = [("queued", r) for r in self._queue] \
                + [("admitting", r) for r in self._admitting] \
                + [("prefill", r) for r in self._prefilling] \
                + [("decode", r) for r in self._active.values()]
        out = []
        for phase, req in rows:
            blocks = shared = 0
            if req.slot is not None and self.kv == "paged" \
                    and cache is not None:
                blocks = int(cache.n_blocks[req.slot])
                shared = int(cache.n_shared[req.slot])
            row = {
                "trace": req.trace,
                "phase": phase,
                "cls": CLASS_NAMES[req.priority],
                "tenant": req.tenant,
                "age_s": round(now - req.t_submit, 3),
                "prompt_tokens": len(req.prompt),
                "tokens": len(req.generated),
                "steps": req.steps,
                "blocks": blocks,
                "blocks_shared": shared,
                "blocks_budget": self._blocks_for(req),
                "preempts": req.preempts,
                "stream": req.sink is not None,
                "deadline_in_s": round(req.deadline - now, 3)
                if req.deadline is not None else None,
            }
            if phase == "prefill":
                row["prefill_off"] = req.pf_off
            out.append(row)
        return out

    def check_kv(self):
        """Invariant sweep over the paged cache INCLUDING the prefix
        cache's resident blocks (tests/soaks): every block is
        exactly one of {trash, free, resident, slot-private} and
        every slot's shared prefix is resident."""
        cache = self.cache_
        if cache is None or self.kv != "paged":
            return
        cache.check(resident=self.prefix_.resident_blocks()
                    if self.prefix_ is not None else ())

    def close(self):
        """Stop the loop, fail every unfinished request, and return
        every in-flight slot/block to the cache (a close with traffic
        in flight must not leak KV blocks — ``cache_.check()`` holds
        afterward)."""
        with self._wake:
            if self._closed:
                return
            self._closed = True
            self._wake.notify()
        loop_dead = True
        if self._thread is not None:
            self._thread.join(30)
            loop_dead = not self._thread.is_alive()
        err = SchedulerError("scheduler closed")
        with self._lock:
            pending = list(self._queue) + list(self._prefilling) \
                + list(self._active.values()) + list(self._admitting)
            aux = list(self._aux) + list(self._prefix_jobs)
            self._queue.clear()
            self._prefilling = []
            self._active.clear()
            self._admitting = []
            self._aux.clear()
            self._prefix_jobs.clear()
            self._exports.clear()
            self._exports_bytes = 0
            self._exports_claimed.clear()
            self._queued_blocks = 0
        host = self.host_
        if host is not None:
            host.clear()   # release the Watcher's host bytes
        for _, _, fut in aux:
            if not fut.done():
                try:
                    fut.set_exception(err)
                except concurrent.futures.InvalidStateError:
                    pass
        cache = self.cache_ if loop_dead else None
        for req in pending:
            if req.slot is not None and cache is not None:
                # the loop thread is dead (joined above): releasing
                # its cache bookkeeping from here cannot race it
                self._release_slot(req, cache)
            req.fail(err)
        if cache is not None:
            self._sync_kv_gauges(cache)
        self._drained.set()
        with self._lock:  # claim the watchdog before joining it
            wd, self._watchdog_thread = self._watchdog_thread, None
        if wd is not None:
            wd.join(5)

    # -- decode loop ----------------------------------------------------

    def _make_cache(self):
        if self.kv == "paged":
            return PagedKVCache(self.forwards, self.max_slots,
                                self.window,
                                block_size=self.block_size,
                                kv_blocks=self.kv_blocks,
                                kv_dtype=self.kv_dtype,
                                tp=self.tp_)
        return SlotKVCache(self.forwards, self.max_slots, self.window)

    def _warm_paged(self, cache):
        """Compile the paged step's (occupancy, depth) bucket ladder
        BEFORE traffic: a bucket's first compile would otherwise land
        inside live serving as a multi-second decode stall (exactly
        the tail latency the buckets exist to remove).  The dummy
        batches are all padding rows — token 0 at position 0 through
        an all-zero block table, i.e. reads and writes confined to
        the reserved trash block."""
        buckets = sorted({_bucket(n, 1, self.max_slots)
                          for n in range(1, self.max_slots + 1)})
        depths = sorted({_bucket(n, 1, cache.blocks_per_slot)
                         for n in range(1, cache.blocks_per_slot + 1)})
        # n-gram-only schedulers verify at ONE fixed spec_k width, so
        # warmup compiles one executable per (B, T) — the pre-PR 20
        # count.  Only model-drafter schedulers (draft head attached)
        # ride the adaptive pow2 width ladder (see _step_verify), and
        # only they warm it.
        if not self.spec:
            ks = []
        elif self._draft_head is not None:
            ks = sorted({_bucket(n, 1, self.spec_k)
                         for n in range(1, self.spec_k + 1)})
        else:
            ks = [_bucket(self.spec_k, 1, self.spec_k)]
        want_h = self._draft_head is not None
        t0 = time.monotonic()
        for b in buckets:
            for t in depths:
                paged_decode_step(
                    self.forwards, cache,
                    numpy.zeros((b, 1), numpy.int32),
                    numpy.zeros((b,), numpy.int32),
                    numpy.zeros((b, t), numpy.int32),
                    numpy.zeros((b,), numpy.float32),
                    numpy.zeros((b,), numpy.int32),
                    numpy.zeros((b,), numpy.uint32),
                    numpy.zeros((b,), numpy.int32),
                    want_hidden=want_h)
                for kk in ks:
                    # the verify ladder rides the same dummy trash-
                    # block convention, one executable per (B, T, k)
                    verify_step_paged(
                        self.forwards, cache,
                        numpy.zeros((b, kk + 1), numpy.int32),
                        numpy.zeros((b,), numpy.int32),
                        numpy.ones((b,), numpy.int32),
                        numpy.zeros((b, t), numpy.int32),
                        numpy.zeros((b,), numpy.float32),
                        numpy.zeros((b,), numpy.int32),
                        numpy.zeros((b,), numpy.uint32),
                        numpy.zeros((b,), numpy.int32),
                        want_hidden=want_h)
        self.info("paged-step warmup: %d occupancy x %d depth x "
                  "%d spec buckets in %.2fs", len(buckets),
                  len(depths), len(ks) + 1, time.monotonic() - t0)

    def _loop(self):
        try:
            cache = self._make_cache()
            if self.prefix_cache:
                self.prefix_ = RadixPrefixCache(self.block_size)
            if self.kv == "paged" and self.warm_buckets:
                self._warm_paged(cache)
            self.cache_ = cache
            if self.kv == "paged":
                self.stats.set_kv_dtype(self.kv_dtype,
                                        cache.bytes_per_token())
        except Exception as e:  # surface init failures to clients
            with self._wake:
                self._closed = True
                pending = list(self._queue)
                self._queue.clear()
            self._ready.set()
            for req in pending:
                req.future.set_exception(SchedulerError(repr(e)))
            raise
        self._ready.set()
        while True:
            with self._wake:
                self._working = False
                while not self._closed and not self._queue \
                        and not self._active and not self._prefilling \
                        and not self._preempts_owed \
                        and not self._aux and not self._prefix_jobs:
                    if self._draining:
                        self._drained.set()
                    # parked KV exports keep a 1 s housekeeping tick
                    # alive so their TTL is enforced even on an idle
                    # prefill replica (no decode work ever wakes it)
                    self._wake.wait(1.0 if self._exports else None)
                    if self._exports:
                        self._sweep_exports_locked()
                if self._closed:
                    return
                # the watchdog measures from here: one iteration =
                # one reap + admit + chunk + decode step
                self._working = True
                self._beat = time.monotonic()
                self._expire_locked()
                if self._exports:
                    self._sweep_exports_locked()
                admits = []
                while self._queue and self._can_admit(
                        cache, self._queue[0]):
                    req = self._queue.popleft()
                    self._queued_blocks -= self._blocks_for(req)
                    if not self._admit_claim(cache, req):
                        # a racing claim in this same batch consumed
                        # the headroom the peek counted — requeue at
                        # the front and retry next boundary
                        self._queue.appendleft(req)
                        self._queued_blocks += self._blocks_for(req)
                        break
                    admits.append(req)
                    self._admitting.append(req)
                # priority preemption: the head of the class-ordered
                # queue outranks an active lower-class request but
                # could not admit — owe ONE eviction at this boundary
                # (one per iteration bounds thrash; the victim's
                # freed blocks seat the head at the next boundary)
                if self._queue and not self._preempts_owed:
                    head = self._queue[0]
                    if head.priority > 0 \
                            and not self._can_admit(cache, head) \
                            and any(r.priority < head.priority
                                    for r in self._active.values()):
                        self._preempts_owed.append(head.priority)
            # jax work OUTSIDE the lock: submit() must never block on
            # a device step
            faults.fire("serving.scheduler.loop")
            self._reap(cache)
            self._do_preempts(cache)
            self._sync_kv_gauges(cache)
            for req in admits:
                self._begin_admit(req, cache)
                with self._lock:
                    self._admitting.remove(req)
            if self._aux:
                self._aux_tick()
            if self._prefix_jobs:
                self._prefix_tick(cache)
            if self._prefilling:
                self._prefill_tick(cache)
            if self._active:
                self._step(cache)

    def _can_admit(self, cache, req):
        """Admission sizing for the head-of-queue request.  A warm
        prompt (prefix-cache hit) needs only its COLD blocks —
        ``ceil(cold_tokens / block_size)`` plus decode headroom — so
        cache hits raise the concurrent-stream ceiling; evictable
        refcount-0 resident blocks count as headroom too."""
        total = self._budget_tokens(req)
        if self.kv != "paged":
            return cache.can_admit(total)
        if not cache.free_slots:
            return False
        need = cache.blocks_needed(total)
        head = cache.free_blocks
        if self.prefix_ is not None:
            if req.kv_import is None:   # imports never match warm
                seq = list(req.prompt) + list(req.generated)
                need -= self.prefix_.peek(
                    seq,
                    max_blocks=(len(seq) - 1) // cache.block_size)
            if self.prefix_evict:
                head += self.prefix_.evictable_blocks()
        return need <= head

    def _admit_claim(self, cache, req):
        """Claim a slot + blocks for one admitted request: pin the
        longest resident prefix (capped so >= 1 token stays cold —
        the first-token logits must come from somewhere), evict
        cold residents if the free list is short, then alloc with
        the matched blocks heading the table."""
        total = self._budget_tokens(req)
        if self.kv != "paged":
            req.slot = cache.alloc(total)
            return req.slot is not None
        handle = None
        # an IMPORT scatters into its leading table blocks — they
        # must be privately owned, never prefix-cache residents, so
        # imports skip the warm match entirely
        if self.prefix_ is not None and req.kv_import is None:
            seq = list(req.prompt) + list(req.generated)
            if self.host_ is not None:
                # promote the host-tier extension FIRST so the match
                # below pins (and the hit stats count) the full warm
                # prefix; net-zero on the free list — each promoted
                # block replaces a cold private block the admission
                # would have claimed anyway
                self._promote_host(cache, seq)
            handle = self.prefix_.match(
                seq, max_blocks=(len(seq) - 1) // cache.block_size)
            self.stats.record_prefix_lookup(len(handle),
                                            cache.block_size)
            if not len(handle):
                handle = None
        matched = len(handle) if handle is not None else 0
        need_new = cache.blocks_needed(total) - matched
        if self.prefix_ is not None and self.prefix_evict \
                and need_new > cache.free_blocks:
            freed = self._evict_prefix(cache,
                                       need_new - cache.free_blocks)
            if freed:
                cache.reclaim(freed)
                self.stats.record_prefix_evict(len(freed))
        slot = cache.alloc(
            total, shared=handle.blocks if handle is not None else ())
        if slot is None:
            if handle is not None:
                self.prefix_.release(handle)
            return False
        req.slot = slot
        req.prefix_handle = handle
        req.pf_matched = matched
        return True

    def _release_slot(self, req, cache, finished=False):
        """Return one request's slot, blocks and prefix pins.  A
        request that FINISHED cleanly donates the full blocks of its
        prompt + generated stream to the prefix cache (insert-on-
        release) — the warm state future identical prefixes match."""
        if req.slot is None:
            if req.prefix_handle is not None:
                self.prefix_.release(req.prefix_handle)
                req.prefix_handle = None
            return
        if self.kv != "paged" or self.prefix_ is None:
            cache.release(req.slot)
        else:
            donate = 0
            seq = None
            if finished:
                seq = list(req.prompt) + list(req.generated)
                # the FINAL token was sampled but never fed back, so
                # its K/V row was never written — donate only blocks
                # fully covered by written positions [0, len - 1)
                # (the same bound the admission match caps at)
                donate = (len(seq) - 1) // cache.block_size \
                    - req.pf_matched
            shared, donated = cache.release(req.slot,
                                            donate=max(0, donate))
            if req.prefix_handle is not None:
                self.prefix_.release(req.prefix_handle)
                req.prefix_handle = None
            if seq is not None and (shared or donated):
                _, rejected = self.prefix_.insert(seq,
                                                  shared + donated)
                if rejected:  # an identical twin donated first
                    cache.reclaim(rejected)
            self._sync_prefix_gauges()
        req.slot = None
        req.pf_matched = 0
        # the hidden the draft head conditions on is per-position
        # host state — a resume re-prefills and re-earns it, and a
        # finished request must not pin a d_model float vector
        req.hid = None

    def _sync_prefix_gauges(self):
        if self.prefix_ is not None:
            self.stats.set_prefix_blocks(self.prefix_.resident,
                                         self.prefix_.shared_blocks())

    def _sync_host_gauges(self):
        if self.host_ is not None:
            self.stats.set_kv_host(self.host_.blocks,
                                   self.host_.bytes)

    def _evict_prefix(self, cache, n):
        """Trie eviction with host-tier demotion: before the device
        blocks go back to the free list, their contents (and int8
        scales) are gathered and parked in the host tier keyed by
        the token path each block completed.  Best-effort — a failed
        demotion only loses warmth, never blocks the eviction the
        admission is waiting on."""
        if self.host_ is None:
            return self.prefix_.evict(n)
        pairs = self.prefix_.evict_with_paths(n)
        if not pairs:
            return []
        demoted = 0
        try:
            layers = cache.export_blocks([b for b, _ in pairs])
            for j, (bid, path) in enumerate(pairs):
                one = {i: {nm: a[j:j + 1]
                           for nm, a in layer.items()}
                       for i, layer in layers.items()}
                if self.host_.put(path, one):
                    demoted += 1
        except Exception as e:
            self.info("host-tier demotion failed: %r", e)
        if demoted:
            self.stats.record_kv_host(demoted=demoted)
        self._sync_host_gauges()
        return [b for b, _ in pairs]

    def _promote_host(self, cache, seq):
        """Promote the host-tier extension of ``seq``'s device-
        resident prefix back into freshly claimed device blocks and
        re-insert them into the trie — the admission's match then
        rides the ordinary warm staging-gather path, and only the
        genuinely cold tail prefills.  Returns blocks promoted (0 on
        any failure: the request simply admits colder)."""
        bs = self.block_size
        limit = (len(seq) - 1) // bs  # >= 1 token must stay cold
        dev = self.prefix_.resident_prefix(seq, limit)
        entries = self.host_.match(seq, len(dev),
                                   max_blocks=limit - len(dev))
        while entries:
            ids = cache.take_free_blocks(len(entries))
            if ids is not None:
                break
            entries.pop()  # promote the longest extension that fits
        if not entries:
            return 0
        try:
            faults.fire("scheduler.kv.promote")
            merged = {
                i: {nm: numpy.concatenate(
                    [e.layers[i][nm].mem for e in entries])
                    for nm in entries[0].layers[i]}
                for i in entries[0].layers}
            cache.import_blocks(ids, merged)
        except Exception as e:
            cache.reclaim(ids)
            self.info("host-tier promotion failed: %r", e)
            return 0
        covered = (len(dev) + len(entries)) * bs
        _, rejected = self.prefix_.insert(list(seq[:covered]),
                                          dev + ids)
        if rejected:  # cannot happen short of a digest collision
            cache.reclaim(rejected)
        self.host_.pop(entries)
        self.stats.record_kv_host(promoted=len(entries))
        self._sync_host_gauges()
        self._sync_prefix_gauges()
        return len(entries)

    def _reap(self, cache):
        """Boundary sweep over the in-flight set: release the slot and
        blocks of every request that was cancelled, crossed its
        deadline mid-decode, or whose future a watchdog trip already
        failed — the other half of the deadline/disconnect contract
        (the future's error alone would still leak KV blocks)."""
        now = time.monotonic()
        with self._lock:
            flight = list(self._prefilling) \
                + list(self._active.values())
        for req in flight:
            if req.future.done():      # watchdog/cancel raced ahead
                self._drop_inflight(req, cache)
            elif req.cancelled:
                self._drop_inflight(req, cache)
                self.stats.record_cancel(len(req.generated),
                                         trace=req.trace)
                req.fail(RequestCancelledError(
                    "cancelled after %d generated tokens"
                    % len(req.generated)))
            elif req.deadline is not None and now > req.deadline:
                self._drop_inflight(req, cache)
                age_ms = (now - req.t_submit) * 1e3
                self.stats.record_expire(age_ms,
                                         tokens=len(req.generated),
                                         trace=req.trace)
                req.fail(DeadlineExceededError(
                    "deadline exceeded after %.0f ms (%d tokens "
                    "generated)" % (age_ms, len(req.generated)),
                    tokens_generated=len(req.generated)))

    def _drop_inflight(self, req, cache):
        """Remove one admitted request from the in-flight set and
        return its slot + blocks to the cache (loop thread only)."""
        with self._lock:
            if req in self._prefilling:
                self._prefilling.remove(req)
            self._active.pop(req.slot, None)
        self._release_slot(req, cache)
        req.pf_seq = req.pf_caches = None
        self._sync_kv_gauges(cache)

    def _do_preempts(self, cache):
        """Evict owed preemptions at this decode boundary: lowest
        priority class first, youngest within it (it loses the least
        re-prefill work — exactly what a priority scheduler should
        sacrifice for a higher-class arrival).  A demand bounded to
        ``below`` with no strictly-lower-class victim is dropped.
        The victim keeps its generated prefix and requeues at the
        front of ITS class, so it resumes as soon as its own freed
        blocks (or better) are available."""
        while True:
            with self._lock:
                if not self._preempts_owed:
                    return
                if not self._active:
                    del self._preempts_owed[:]  # no targets: demand
                    return                      # dies here
                below = self._preempts_owed.pop(0)
                victims = [r for r in self._active.values()
                           if below is None or r.priority < below]
                if not victims:
                    continue   # bounded demand, no qualifying victim
                req = max(victims,
                          key=lambda r: (-r.priority, r.t_admit,
                                         r.slot))
                self._active.pop(req.slot, None)
            self._release_slot(req, cache)
            req.preempts += 1
            self.stats.record_preempt(len(req.generated),
                                      cls=CLASS_NAMES[req.priority],
                                      trace=req.trace)
            self._sync_kv_gauges(cache)
            with self._lock:
                self._enqueue_locked(req, front=True)
                self._queued_blocks += self._blocks_for(req)

    def _watchdog_loop(self):
        """Detect a stuck decode iteration and fail the pending
        futures — clients get a fast 5xx instead of a hung socket;
        when (if) the loop unsticks, :meth:`_reap` returns the
        zombies' slots and blocks to the pool."""
        period = max(0.02, min(1.0, self.watchdog / 8.0))
        while True:
            time.sleep(period)
            with self._lock:
                if self._closed:
                    return
                beat, working = self._beat, self._working
                tripped = self._tripped_beat
            if not working or beat is None or beat == tripped:
                continue
            stalled = time.monotonic() - beat
            if stalled <= self.watchdog:
                continue
            with self._lock:
                self._tripped_beat = beat
                victims = [r for r in list(self._queue)
                           + list(self._prefilling)
                           + list(self._active.values())
                           + list(self._admitting)
                           if not r.future.done()]
            err = SchedulerError(
                "decode loop stalled %.1fs (watchdog %.1fs) — "
                "request failed instead of hanging" % (stalled,
                                                       self.watchdog))
            for req in victims:
                req.fail(err)
            self.stats.record_watchdog_trip(len(victims), stalled)
            self.warning(
                "decode loop stalled %.1fs — failed %d pending "
                "requests", stalled, len(victims))

    def _sync_kv_gauges(self, cache):
        if self.kv == "paged":
            self.stats.set_kv_blocks(cache.used_blocks,
                                     cache.free_blocks)

    def _expire_locked(self):
        now = time.monotonic()
        kept = collections.deque()
        while self._queue:
            req = self._queue.popleft()
            if req.future.done():
                # a watchdog trip failed it while queued — drop it
                self._queued_blocks -= self._blocks_for(req)
            elif req.deadline is not None and now > req.deadline:
                self._queued_blocks -= self._blocks_for(req)
                queued_ms = (now - req.t_submit) * 1e3
                self.stats.record_expire(queued_ms,
                                         tokens=len(req.generated),
                                         trace=req.trace)
                req.fail(DeadlineExceededError(
                    "queued %.0f ms without a free slot" % queued_ms,
                    tokens_generated=len(req.generated)))
            else:
                kept.append(req)
        self._queue = kept

    def _staging_width(self, p_len, chunk):
        """Width of the batch-1 staging K/V row a prompt prefills
        into: the power-of-two bucket of the prompt, floored so it
        tiles both the chunk width and (paged) the block size."""
        bs = self.block_size if self.kv == "paged" else 1
        floor = max(self.prefill_bucket, bs, chunk or 1)
        return _bucket(p_len, floor, 1 << 30)

    def _begin_admit(self, req, cache):
        """Route one joining request: short sequences prefill
        one-shot; long ones start the chunked-prefill ride-along.  A
        preempted request resumes here — its prefill sequence is
        prompt + the kept generated prefix, so the re-prefill rebuilds
        exactly the K/V its decode steps had written before eviction."""
        req.t_admit = time.monotonic()
        if req.kv_import is not None and not req.preempts:
            # disaggregated handoff: the exported blocks ARE the
            # prefill — scatter them in and go straight to decode.
            # A preempt-resume of an imported request falls through
            # to the normal re-prefill below instead (its blocks
            # were freed; the chain recomputes the identical K/V)
            self._admit_import(req, cache)
            return
        seq = list(req.prompt) + list(req.generated)
        if req.preempts and req.generated:
            self.stats.record_resume(len(seq))
        req.pf_seq = seq
        p_len = len(seq)
        if self._tron:
            # the queue-wait span [submit, admit] plus the admission
            # decision: cold vs prefix-warm and the blocks claimed —
            # the first two entries of a request's phase timeline
            need = self._blocks_for(req)
            reqtrace.record(
                req.trace, "queue",
                duration=req.t_admit - req.t_submit,
                cls=CLASS_NAMES[req.priority],
                tenant=req.tenant,
                resume=bool(req.preempts))
            reqtrace.record(
                req.trace, "admit", slot=req.slot, tokens=p_len,
                warm_blocks=req.pf_matched,
                blocks_claimed=max(0, need - req.pf_matched),
                resume=bool(req.preempts))
        if req.pf_matched:
            self._admit_warm(req, cache)
            return
        chunk = self.prefill_chunk
        if not chunk or p_len <= chunk:
            self._admit_oneshot(req, cache)
            return
        from veles_tpu import dtypes
        req.pf_chunk = chunk
        req.pf_width = self._staging_width(p_len, chunk)
        req.pf_off = 0
        try:
            req.pf_caches = {
                i: u.init_cache(1, req.pf_width,
                                dtypes.compute_dtype())
                for i, u in enumerate(self.forwards)
                if hasattr(u, "init_cache")}
        except Exception as e:
            self._retire(req, cache, error=e)
            return
        with self._lock:  # close() swaps the list under the same lock
            self._prefilling.append(req)

    def _admit_warm(self, req, cache):
        """Prefix-cache hit: the matched blocks already hold the K/V
        of tokens [0, matched · block_size) — GATHER them into the
        staging row and ride the chunked-prefill path for the cold
        tail only (near-zero TTFT when the tail is short).  The
        chunk narrows to block_size so every offset stays
        chunk-aligned from the warm boundary."""
        from veles_tpu import dtypes
        bs = self.block_size
        p_len = len(req.pf_seq)
        req.pf_chunk = min(self.prefill_chunk, bs)
        req.pf_width = self._staging_width(p_len, self.prefill_chunk)
        req.pf_off = req.pf_matched * bs
        try:
            req.pf_caches = {
                i: u.init_cache(1, req.pf_width,
                                dtypes.compute_dtype())
                for i, u in enumerate(self.forwards)
                if hasattr(u, "init_cache")}
            req.pf_caches = cache.load_staging(
                req.pf_caches, req.prefix_handle.blocks)
        except Exception as e:
            self._retire(req, cache, error=e)
            return
        with self._lock:
            self._prefilling.append(req)

    def _admit_oneshot(self, req, cache):
        """Prefill one joining request's sequence (prompt, plus the
        generated prefix on resume) in a single compiled pass and emit
        its next token (the TTFT edge)."""
        p_len = len(req.pf_seq)
        width = self._staging_width(p_len, 0)
        # the SEQUENCE array stays inside the positional table; the
        # staging cache may be wider (insert trims it back)
        p_w = min(width, max(self.window, p_len))
        padded = numpy.zeros((1, p_w), numpy.int32)
        padded[0, :p_len] = req.pf_seq
        t0 = time.perf_counter()
        try:
            faults.fire("serving.scheduler.prefill")
            row_caches, last = prefill(
                self.forwards, padded, prompt_lens=[p_len],
                window=width, tp=self.tp_)
        except Exception as e:
            self._retire(req, cache, error=e)
            return
        if self._tron:
            reqtrace.record(req.trace, "prefill",
                            duration=time.perf_counter() - t0,
                            tokens=p_len)
        self._finish_admit(req, cache, row_caches, last)

    def _prefill_tick(self, cache):
        """Advance the oldest mid-prefill request by ONE chunk — the
        per-iteration decode-stall bound; the decode step for every
        in-flight stream runs right after, in the same iteration."""
        with self._lock:
            if not self._prefilling:  # reaped between check and tick
                return
            req = self._prefilling[0]
        p_len = len(req.pf_seq)
        c = req.pf_chunk
        off = req.pf_off
        end = min(off + c, p_len)
        clen = end - off
        padded = numpy.zeros((1, c), numpy.int32)
        padded[0, :clen] = req.pf_seq[off:end]
        kw = _bucket(off + c, c, req.pf_width)
        t0 = time.perf_counter()
        try:
            faults.fire("serving.scheduler.prefill")
            req.pf_caches, last = prefill_chunk(
                self.forwards, padded, off, [clen], req.pf_caches,
                key_width=kw, tp=self.tp_)
        except Exception as e:
            with self._lock:
                if req in self._prefilling:
                    self._prefilling.remove(req)
            self._retire(req, cache, error=e)
            return
        self.stats.record_prefill_chunk(
            clen, (time.perf_counter() - t0) * 1e3)
        if self._tron:
            reqtrace.record(req.trace, "prefill_chunk",
                            duration=time.perf_counter() - t0,
                            off=off, tokens=clen)
        req.pf_off = end
        if end >= p_len:
            with self._lock:
                if req in self._prefilling:
                    self._prefilling.remove(req)
            self._finish_admit(req, cache, req.pf_caches, last)

    def _finish_admit(self, req, cache, row_caches, last):
        """Insert the prefilled staging row and emit the next token:
        draw 0 on a fresh admission, draw ``len(generated)`` on a
        preempt-resume — exactly the counter the decode step would
        have folded, so the resumed stream never forks."""
        try:
            if self.kv == "paged":
                # a warm admission skips its shared prefix blocks —
                # they are the prefix cache's, and already hold
                # exactly these rows
                cache.insert(req.slot, row_caches, len(req.pf_seq),
                             from_block=req.pf_matched)
            else:
                cache.insert(req.slot, row_caches, len(req.pf_seq))
        except Exception as e:
            self._retire(req, cache, error=e)
            return
        if req.export_only:
            # prefill-role terminus: the blocks now hold the whole
            # prompt's K/V — gather them raw + the first-token
            # logits, park the record, and hand the blocks back
            self._retire_export(req, cache, last)
            return
        req.pf_caches = None
        req.pf_seq = None
        self._activate(req, cache, last)

    def _activate(self, req, cache, last):
        """Emit the first token from the last-position logits (draw
        ``len(generated)`` of the request's stream) and join the
        active decode set — the shared tail of a finished prefill
        and an adopted KV import."""
        tok = int(numpy.asarray(first_tokens(
            last, [req.temperature], [req.top_k], [req.seed],
            counts=[len(req.generated)]))[0])
        self._emit(req, tok)
        if req.t_first is None:  # TTFT is the FIRST first-token only
            req.t_first = time.monotonic()
            self.stats.record_first_token(
                (req.t_first - req.t_submit) * 1e3,
                (req.t_admit - req.t_submit) * 1e3,
                cls=CLASS_NAMES[req.priority])
            if self._tron:
                reqtrace.record(
                    req.trace, "first_token",
                    ttft_ms=round(
                        (req.t_first - req.t_submit) * 1e3, 3))
        with self._lock:
            self._active[req.slot] = req
        self._maybe_finish(req, cache)

    def _admit_import(self, req, cache):
        """Adopt a KV export record (disaggregated decode half): the
        exported blocks scatter RAW into the slot's leading table
        blocks — byte-identical resident state to the exporting
        replica, no prefill pass — and the first token samples from
        the exported logits with this request's sampler settings
        (draw 0 of its stream, the exact fold the colocated path
        uses)."""
        imp = req.kv_import
        try:
            faults.fire("serving.scheduler.kv_import")
            n = cache.blocks_needed(imp["length"])
            ids = [int(b) for b in cache.tables[req.slot, :n]]
            cache.import_blocks(ids, imp["layers"])
        except Exception as e:
            self._retire(req, cache, error=e)
            return
        if self._tron:
            reqtrace.record(
                req.trace, "queue",
                duration=req.t_admit - req.t_submit,
                cls=CLASS_NAMES[req.priority],
                tenant=req.tenant, resume=False)
            reqtrace.record(
                req.trace, "kv_import", slot=req.slot,
                tokens=int(imp["length"]), blocks=len(ids))
        last = numpy.asarray(imp["logits"],
                             numpy.float32).reshape(1, -1)
        self._activate(req, cache, last)

    def _retire_export(self, req, cache, last):
        """Finish a prefill-export request: gather the slot's blocks
        raw (scales riding along under int8) plus the last-position
        logits into a handle-addressed record, then release the slot
        — donating the prompt's blocks to the prefix cache like any
        finished request, so repeat prompts prefill warm on this
        replica too."""
        p_len = len(req.pf_seq)
        try:
            faults.fire("serving.scheduler.kv_export")
            n = cache.blocks_needed(p_len)
            ids = [int(b) for b in cache.tables[req.slot, :n]]
            from veles_tpu.serving.disagg import mint_handle
            handle = mint_handle()
            record = {
                "handle": handle,
                "prompt": list(req.prompt),
                "length": p_len,
                "kv_dtype": self.kv_dtype,
                "block_size": self.block_size,
                "logits": numpy.asarray(last,
                                        numpy.float32)[0].copy(),
                "layers": cache.export_blocks(ids),
                "t": time.monotonic(),
            }
        except Exception as e:
            self._retire(req, cache, error=e)
            return
        req.pf_caches = None
        req.pf_seq = None
        with self._lock:
            self._active.pop(req.slot, None)
        self._release_slot(req, cache, finished=True)
        self._sync_kv_gauges(cache)
        now = time.monotonic()
        from veles_tpu.serving.disagg import record_nbytes
        record["bytes"] = record_nbytes(record)
        with self._lock:
            self._sweep_exports_locked(now)
            capped = 0
            while self._exports and self._exports_bytes \
                    + record["bytes"] > self.kv_export_bytes:
                # oldest unclaimed record pays for the byte budget
                oldest = min(self._exports,
                             key=lambda h: self._exports[h]["t"])
                self._exports_bytes -= \
                    self._exports[oldest].get("bytes", 0)
                del self._exports[oldest]
                capped += 1
            if capped:
                # a cap eviction is an unfetched loss like an
                # expiry, just paid early — same alertable series
                self.stats.record_kv_export_expired(capped)
            self._exports[handle] = record
            self._exports_bytes += record["bytes"]
            self.stats.set_kv_exports_pending(len(self._exports))
        if self._tron:
            reqtrace.record(
                req.trace, "kv_export", tokens=p_len, blocks=n,
                total_s=round(now - req.t_submit, 6))
        if not req.future.done():
            try:
                req.future.set_result({
                    "handle": handle, "prompt_tokens": p_len,
                    "blocks": n})
            except concurrent.futures.InvalidStateError:
                pass

    def _step(self, cache):
        """Advance every active request one token through the shared
        compiled step, then retire finished ones at the boundary."""
        with self._lock:
            active = dict(self._active)
        if not active:
            return
        faults.fire("serving.scheduler.step")
        if self.kv == "paged":
            self._step_paged(cache, active)
        else:
            self._step_dense(cache, active)

    def _emit(self, req, tok):
        """Accept one token: append to the request's stream AND push
        it to the live subscription (submit(stream=True)) in the same
        boundary — what makes SSE concatenation bit-identical to the
        batch reply (a preempt-resume re-prefills but never re-emits;
        only newly drawn tokens pass through here)."""
        req.generated.append(tok)
        if req.sink is not None:
            req.sink(tok)

    def _fill_row(self, arrays, j, req):
        toks, pos, temps, topks, seeds, counts = arrays
        toks[j, 0] = req.generated[-1]
        pos[j] = len(req.prompt) + len(req.generated) - 1
        temps[j] = req.temperature
        topks[j] = req.top_k
        seeds[j] = req.seed
        counts[j] = len(req.generated)

    def _pick_model(self, req):
        """Per-slot drafter arbitration: take the model head unless
        its accept-rate EMA has fallen below the n-gram proposer's.
        Unseen drafters score an optimistic 1.0 (each gets tried
        before being judged), ties go to the model — so a slot whose
        model drafts reject drifts to n-gram and drifts back the
        moment n-gram does worse."""
        em = req.accept_ema.get("model")
        en = req.accept_ema.get("ngram")
        return (1.0 if em is None else em) \
            >= (1.0 if en is None else en)

    def _draft(self, active):
        """Propose draft tokens per slot — capped so accepting every
        draft plus the correction token never exceeds the request's
        step budget (the positions stay inside the blocks claimed at
        admission).  Each slot drafts up to its ADAPTIVE ``draft_k``
        (accept-rate EMA; see __init__) from its arbitrated source:
        the Medusa head batched over every slot with a live hidden
        state, or n-gram prompt lookup through the request's memoized
        trailing-gram index.  Returns ``(drafts, sources)`` —
        {slot: tokens} and {slot: "model"|"ngram"}."""
        drafts, sources = {}, {}
        model_out = {}
        if self._draft_head is not None:
            rows = [s for s in sorted(active)
                    if active[s].hid is not None]
            if rows:
                out = self._draft_head.propose(
                    numpy.stack([active[s].hid for s in rows]))
                for j, slot in enumerate(rows):
                    model_out[slot] = out[j]
        for slot, req in active.items():
            room = req.steps - len(req.generated) - 1
            if room < 1:
                continue
            if req.draft_k < 1:
                req.draft_k = self.spec_k  # start optimistic
            limit = min(req.draft_k, room)
            d = None
            if slot in model_out and self._pick_model(req):
                d = [int(t) for t in model_out[slot][:limit]]
                sources[slot] = "model"
            if not d:
                if req.gram_ix is None:
                    req.gram_ix = NgramIndex(
                        self._proposer.max_ngram,
                        self._proposer.min_ngram)
                d = self._proposer.propose(
                    list(req.prompt) + list(req.generated), limit,
                    index=req.gram_ix)
                sources[slot] = "ngram"
            if d:
                drafts[slot] = d[:limit]
            else:
                sources.pop(slot, None)
        return drafts, sources

    def _adapt_draft_k(self, req, drafted, accepted, drafter):
        """Post-verify accept-rate bookkeeping for one slot: blend
        this verify's accept rate into the slot's per-drafter EMA
        (weight ``draft_ema``), then steer the slot's draft length —
        halve toward ``draft_k_min`` below ``draft_shrink`` (stop
        paying verify width for drafts that keep rejecting), double
        toward ``spec_k`` above ``draft_grow``.  Powers of two only,
        so every length lands on a warmed verify bucket."""
        rate = accepted / drafted
        prev = req.accept_ema.get(drafter)
        ema = rate if prev is None \
            else (1.0 - self.draft_ema) * prev + self.draft_ema * rate
        req.accept_ema[drafter] = ema
        if ema < self.draft_shrink:
            req.draft_k = max(self.draft_k_min, req.draft_k >> 1)
        elif ema > self.draft_grow:
            req.draft_k = min(self.spec_k, req.draft_k << 1)
        self.stats.record_spec(drafted, accepted, drafter=drafter,
                               draft_k=req.draft_k)

    def _meter_step(self, active, cache, dt):
        """Step-boundary usage attribution (PR 17 metering): each
        active request charges its tenant KV-blocks-held x the step's
        wall time, plus an even 1/n split of the step's duration as
        compute-seconds.  Sampled here — not at retire — so a
        long-lived stream's HBM residency accrues while it runs, and
        a preempted request stops being charged the moment its
        blocks are released."""
        if not self._metering or not active or dt <= 0:
            return
        share = dt / len(active)
        usage = {}
        for slot, req in active.items():
            if self.kv == "paged":
                blocks = int(cache.n_blocks[slot])
            else:
                blocks = -(-(len(req.prompt) + len(req.generated))
                           // self.block_size)
            rec = usage.setdefault(req.tenant or "anon", [0.0, 0.0])
            rec[0] += blocks * dt
            rec[1] += share
        self.stats.record_tenant_step(usage)

    def _step_paged(self, cache, active):
        """Packed step: ONLY the active slots ride the batch, padded
        to a power-of-two occupancy bucket; the attended range is the
        power-of-two block bucket of the deepest request."""
        if self.spec:
            drafts, sources = self._draft(active)
            if drafts:
                self._step_verify(cache, active, drafts, sources)
                return
        slots = sorted(active)
        n = len(slots)
        b = _bucket(n, 1, self.max_slots)
        bs = cache.block_size
        deepest = max(len(active[s].prompt) + len(active[s].generated)
                      for s in slots)
        t = _bucket(-(-deepest // bs), 1, cache.blocks_per_slot)
        toks = numpy.zeros((b, 1), numpy.int32)
        pos = numpy.zeros((b,), numpy.int32)
        temps = numpy.zeros((b,), numpy.float32)
        topks = numpy.zeros((b,), numpy.int32)
        seeds = numpy.zeros((b,), numpy.uint32)
        counts = numpy.zeros((b,), numpy.int32)
        tables = numpy.zeros((b, t), numpy.int32)
        arrays = (toks, pos, temps, topks, seeds, counts)
        for j, slot in enumerate(slots):
            self._fill_row(arrays, j, active[slot])
        tables[:n] = cache.table_rows(slots, t)
        want_h = self._draft_head is not None
        t0 = time.perf_counter()
        got = paged_decode_step(
            self.forwards, cache, toks, pos, tables, temps, topks,
            seeds, counts, want_hidden=want_h)
        if want_h:
            nxt, hid = got
            hid = numpy.asarray(hid)
        else:
            nxt = got
        nxt = numpy.asarray(nxt)
        dt = time.perf_counter() - t0
        # plain decode: every active slot emits exactly one token
        self.stats.record_step(n, b, tokens=n, duration_s=dt)
        self._meter_step(active, cache, dt)
        for j, slot in enumerate(slots):
            req = active[slot]
            if want_h:
                # hidden of the position just decoded — what the
                # Medusa heads condition on next iteration
                req.hid = hid[j]
            self._emit(req, int(nxt[j]))
            self._maybe_finish(req, cache)
        if self._tron:
            emitted = {}
            for s in slots:  # batch rows may SHARE a client trace id
                tr = active[s].trace
                emitted[tr] = emitted.get(tr, 0) + 1
            reqtrace.record_step(emitted, duration=dt,
                                 mode="decode", slots=n, bucket=b)

    def _step_verify(self, cache, active, drafts, sources):
        """Speculative step: every active slot rides ONE batched
        verify pass — its pending token plus its drafts (slots
        without a draft run a plain width-1 decode inside the same
        batch).  The occupancy/depth buckets grow a power-of-two
        draft-width axis k; acceptance keeps the longest matched
        prefix plus the correction sample, so the emitted stream is
        bit-identical to spec-off decoding while one pass can emit
        up to k + 1 tokens."""
        slots = sorted(active)
        n = len(slots)
        b = _bucket(n, 1, self.max_slots)
        # adaptive draft width — MODEL-DRAFTER schedulers only: the
        # verify runs at the power-of-two bucket of the widest draft
        # BUDGET among drafting slots, so when every slot's EMA
        # controller has shrunk its draft_k the pass stops paying
        # spec_k-wide sampling for one-token drafts.  Keying on
        # draft_k (not raw draft lengths) keeps un-shrunk batches on
        # the spec_k-wide executable; the ladder is bounded at
        # log2(spec_k) + 1 per (B, T) and only exists where a draft
        # head is attached — n-gram-only schedulers keep the ONE
        # fixed-width executable (drafts pad up, ``lens`` masks), so
        # the flipped-on spec default compiles nothing extra.
        if self._draft_head is not None:
            k = _bucket(max(active[s].draft_k for s in drafts),
                        1, self.spec_k)
        else:
            k = _bucket(self.spec_k, 1, self.spec_k)
        bs = cache.block_size
        deepest = max(len(active[s].prompt)
                      + len(active[s].generated) for s in slots) + k
        t = _bucket(-(-deepest // bs), 1, cache.blocks_per_slot)
        toks = numpy.zeros((b, k + 1), numpy.int32)
        pos = numpy.zeros((b,), numpy.int32)
        lens = numpy.ones((b,), numpy.int32)
        temps = numpy.zeros((b,), numpy.float32)
        topks = numpy.zeros((b,), numpy.int32)
        seeds = numpy.zeros((b,), numpy.uint32)
        counts = numpy.zeros((b,), numpy.int32)
        tables = numpy.zeros((b, t), numpy.int32)
        for j, slot in enumerate(slots):
            req = active[slot]
            d = drafts.get(slot, ())[:k]
            toks[j, 0] = req.generated[-1]
            if d:
                toks[j, 1:1 + len(d)] = d
            pos[j] = len(req.prompt) + len(req.generated) - 1
            lens[j] = 1 + len(d)
            temps[j] = req.temperature
            topks[j] = req.top_k
            seeds[j] = req.seed
            counts[j] = len(req.generated)
        tables[:n] = cache.table_rows(slots, t)
        want_h = self._draft_head is not None
        t0 = time.perf_counter()
        got = verify_step_paged(
            self.forwards, cache, toks, pos, lens, tables, temps,
            topks, seeds, counts, want_hidden=want_h)
        if want_h:
            nxt, hid = got
            hid = numpy.asarray(hid)
        else:
            nxt = got
        nxt = numpy.asarray(nxt)
        dt = time.perf_counter() - t0
        # metered BEFORE acceptance retires finished slots — the
        # step's residency belongs to everyone who rode the batch
        self._meter_step(active, cache, dt)
        emitted = {}
        for j, slot in enumerate(slots):
            req = active[slot]
            d = list(drafts.get(slot, ()))[:k]
            out = accept_drafts(d, nxt[j, :len(d) + 1])
            before = len(req.generated)
            for tok in out:
                self._emit(req, int(tok))
                if len(req.generated) >= req.steps \
                        or (req.stop_token is not None
                            and int(tok) == req.stop_token):
                    break
            done = len(req.generated) - before
            if want_h and done > 0:
                # hidden of the LAST position this verify scored and
                # kept — row [j, done-1] conditioned the token now
                # pending, so the Medusa heads read it next iteration
                req.hid = hid[j, done - 1]
            if d:
                self._adapt_draft_k(req, len(d), len(out) - 1,
                                    sources.get(slot, "ngram"))
            emitted[req.trace] = emitted.get(req.trace, 0) + done
            self._maybe_finish(req, cache)
        # recorded AFTER acceptance so goodput counts what the verify
        # actually emitted (a fully-rejected batch is 0 good tokens)
        self.stats.record_step(n, b, tokens=sum(emitted.values()),
                               duration_s=dt)
        if self._tron:
            reqtrace.record_step(emitted, duration=dt, mode="verify",
                                 slots=n, bucket=b, k=k)

    def _step_dense(self, cache, active):
        """Legacy full-batch step: free slots decode garbage rows."""
        s = self.max_slots
        toks = numpy.zeros((s, 1), numpy.int32)
        pos = numpy.zeros((s,), numpy.int32)
        temps = numpy.zeros((s,), numpy.float32)
        topks = numpy.zeros((s,), numpy.int32)
        seeds = numpy.zeros((s,), numpy.uint32)
        counts = numpy.zeros((s,), numpy.int32)
        arrays = (toks, pos, temps, topks, seeds, counts)
        for slot, req in active.items():
            self._fill_row(arrays, slot, req)
        t0 = time.perf_counter()
        nxt = numpy.asarray(slot_decode_step(
            self.forwards, cache, toks, pos, temps, topks, seeds,
            counts))
        dt = time.perf_counter() - t0
        self.stats.record_step(len(active), s, tokens=len(active),
                               duration_s=dt)
        self._meter_step(active, cache, dt)
        for slot, req in active.items():
            self._emit(req, int(nxt[slot]))
            self._maybe_finish(req, cache)
        if self._tron:
            emitted = {}
            for r in active.values():
                emitted[r.trace] = emitted.get(r.trace, 0) + 1
            reqtrace.record_step(emitted, duration=dt, mode="decode",
                                 slots=len(active), bucket=s)

    def _maybe_finish(self, req, cache, error=None):
        done = error is not None \
            or len(req.generated) >= req.steps \
            or (req.stop_token is not None
                and req.generated[-1] == req.stop_token)
        if done:
            self._retire(req, cache, error=error)

    def _retire(self, req, cache, error=None):
        with self._lock:
            self._active.pop(req.slot, None)
        self._release_slot(req, cache, finished=error is None)
        self._sync_kv_gauges(cache)
        if self._metering:
            # token attribution happens for ERRORS too — the prefill
            # and decode compute was spent either way, and a bill
            # that forgets failures undercharges the tenant causing
            # them
            self.stats.record_tenant_tokens(
                req.tenant, prompt=len(req.prompt),
                generated=len(req.generated))
        if self._tron:
            # an INSTANT at the retire boundary ("duration" would
            # backdate it into a request-spanning bar): total_s is
            # the whole submit->retire wall time as an attribute
            reqtrace.record(
                req.trace, "retire", tokens=len(req.generated),
                total_s=round(time.monotonic() - req.t_submit, 6),
                preempts=req.preempts,
                outcome="ok" if error is None
                else type(error).__name__)
        if error is not None:
            req.fail(error if isinstance(error, SchedulerError)
                     else SchedulerError(repr(error)))
            return
        if req.future.done():
            # watchdog/cancel failed it first — the tokens are moot
            return
        now = time.monotonic()
        self.stats.record_complete(
            len(req.generated), now - req.t_submit,
            (req.t_first - req.t_submit) * 1e3,
            (req.t_admit - req.t_submit) * 1e3,
            cls=CLASS_NAMES[req.priority], trace=req.trace)
        try:
            req.future.set_result(list(req.prompt) + req.generated)
        except concurrent.futures.InvalidStateError:
            pass
