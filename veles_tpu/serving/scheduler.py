"""Continuous-batching inference scheduler.

Requests queue on :meth:`InferenceScheduler.submit` (any thread) and
are decoded by ONE background loop (all jax work — ``Array.devmem``
uploads and the compile caches are not thread-safe against concurrent
mutation, and a single loop is what lets every in-flight request share
one compiled step):

1. **admit** — while capacity allows, the oldest queued request
   claims a slot.  Under the default PAGED KV cache
   (:class:`serving.kv_slots.PagedKVCache`) admission is
   memory-proportional: the request also claims its whole block
   budget (``ceil((prompt + steps) / block_size)`` blocks), so short
   requests pack many more concurrent streams into the same HBM than
   the dense window-per-slot layout;
2. **prefill** — prompts up to ``prefill_chunk`` prefill in ONE
   compiled pass; longer prompts prefill in ``prefill_chunk``-token
   CHUNKS, at most one chunk per loop iteration, INTERLEAVED with the
   decode step below (Sarathi-style chunked prefill) — a joining long
   prompt stalls in-flight decode streams by one chunk per iteration,
   not by its whole prefill, which flattens the TTFT tail of short
   requests stuck behind long ones.  Either way the K/V staging row
   is inserted into the cache and the first token samples from the
   final logits (the TTFT edge);
3. **step** — active slots advance one token through the shared
   compiled step.  The paged path packs ONLY the active slots into a
   power-of-two occupancy bucket and bounds attention by a
   power-of-two block bucket over the deepest request
   (:func:`serving.engine.paged_decode_step`), so a half-empty batch
   of shallow requests pays neither full-batch nor full-window
   compute; the dense fallback runs the fixed full-slot step;
4. **retire** — a slot that generated its stop token or hit its step
   limit completes its future and frees slot + blocks at the token
   boundary, where the next queued request joins.

Admission control: a full queue raises :class:`QueueFullError` (HTTP
503) at submit; a request still queued past its deadline fails with
:class:`DeadlineExceededError` (HTTP 408).  Greedy requests keep
exact determinism (each request's attention sees only its own cache
rows/blocks, and sampling is row-wise, so token streams are
independent of slot placement, packing order and co-tenants);
sampled requests are reproducible per seed — though the stream
differs from the single-user ``generate()`` path's (one fold per
generated token here vs one split per lockstep buffer position
there).

Request lifecycle (fault tolerance): every request carries a
whole-request **deadline** (``root.common.serving.request_timeout``,
overridable per submit) enforced at chunk/decode boundaries — an
expired request frees its slot and blocks and fails with
:class:`DeadlineExceededError` carrying the tokens generated so far
(HTTP 408 material).  A client that went away can :meth:`cancel` its
future; the loop releases the resources at the next boundary.  The
scheduler can **preempt** an active request
(:meth:`request_preempt`): its blocks return to the pool, its
generated-token prefix is kept, and on re-admission prompt + prefix
re-prefill through the chunked-prefill path and decoding continues —
the token stream is bit-identical to the uninterrupted run because
token ``t`` is always drawn with ``fold_in(key(seed), t)`` regardless
of slot or cache placement.  A **watchdog** thread detects a stuck
decode step (``root.common.serving.watchdog`` seconds) and fails
pending requests instead of hanging their clients; block-pressure
**load shedding** (``shed_block_factor``) turns hopeless submits into
deterministic 503s before they queue; and :meth:`drain` closes
admission (503 + Retry-After), finishes everything in flight and
signals ``drained`` — the rolling-restart hook behind ``POST
/drain``.  Injection points (``serving.scheduler.*`` — see
:mod:`veles_tpu.faults`) let tier-1 exercise every one of these paths
deterministically.

Config knobs (``root.common.serving.*``, overridable per scheduler):
``kv`` ("paged"/"dense"), ``block_size`` (tokens per KV block,
default 16), ``kv_blocks`` (pool capacity in blocks; default the
dense-equivalent ``max_slots · ceil(window / block_size)``),
``prefill_chunk`` (chunk width in tokens, rounded up to a power of
two; 0 disables chunking, default 64), ``request_timeout`` /
``watchdog`` / ``shed_block_factor`` (lifecycle knobs above; 0
disables each).
"""

import collections
import concurrent.futures
import os
import threading
import time

import numpy

from veles_tpu import faults
from veles_tpu.logger import Logger
from veles_tpu.serving.engine import (
    first_tokens, paged_decode_step, slot_decode_step)
from veles_tpu.serving.kv_slots import (
    PagedKVCache, SlotKVCache, paged_supported)
from veles_tpu.serving.metrics import ServingMetrics
from veles_tpu.serving.prefill import (
    chunked_supported, prefill, prefill_chunk, serving_supported,
    serving_window)


class SchedulerError(Exception):
    """Base serving failure (maps to HTTP 500)."""
    http_status = 500


class QueueFullError(SchedulerError):
    """Admission control: queue-depth cap hit or block-pressure shed
    (HTTP 503; ``retry_after`` seeds the Retry-After header)."""
    http_status = 503
    retry_after = 1


class DrainingError(QueueFullError):
    """Admission closed for a graceful drain (HTTP 503) — the caller
    should retry against another replica."""
    retry_after = 5


class DeadlineExceededError(SchedulerError):
    """The request crossed its deadline — still queued
    (``tokens_generated == 0``) or mid-decode (HTTP 408; the partial
    count rides the error so clients know what they paid for)."""
    http_status = 408

    def __init__(self, message, tokens_generated=0):
        super(DeadlineExceededError, self).__init__(message)
        self.tokens_generated = int(tokens_generated)


class RequestCancelledError(SchedulerError):
    """The request was cancelled (client disconnect/abandon); its
    slot and KV blocks were released at the next boundary."""


def _bucket(n, floor, cap):
    """Pad widths/counts to power-of-two buckets so the compiled
    executable count stays O(log) across arbitrary clients."""
    b = max(int(floor), 1)
    while b < n:
        b *= 2
    return min(b, cap)


def _serving_conf(name, default):
    from veles_tpu.config import root
    return root.common.serving.get(name, default)


class _Request(object):
    __slots__ = ("prompt", "steps", "temperature", "top_k",
                 "stop_token", "seed", "deadline", "future", "slot",
                 "generated", "cancelled", "preempts", "t_submit",
                 "t_admit", "t_first", "pf_seq", "pf_caches",
                 "pf_off", "pf_width", "pf_chunk")

    def __init__(self, prompt, steps, temperature, top_k, stop_token,
                 seed, deadline):
        self.prompt = prompt
        self.steps = steps
        self.temperature = temperature
        self.top_k = top_k
        self.stop_token = stop_token
        self.seed = seed
        self.deadline = deadline
        self.future = concurrent.futures.Future()
        self.slot = None
        self.generated = []
        self.cancelled = False   # client gone — reap at next boundary
        self.preempts = 0        # times evicted (resume re-prefills)
        self.t_submit = time.monotonic()
        self.t_admit = None
        self.t_first = None
        # chunked-prefill progress (None while queued / one-shot);
        # pf_seq is the token sequence being prefilled — the prompt,
        # plus the generated prefix when resuming after a preemption
        self.pf_seq = None
        self.pf_caches = None
        self.pf_off = 0
        self.pf_width = 0
        self.pf_chunk = 0

    def fail(self, error):
        """Set the future's exception unless a racing path (watchdog,
        cancel) beat us to it."""
        if not self.future.done():
            try:
                self.future.set_exception(error)
            except concurrent.futures.InvalidStateError:
                pass


class InferenceScheduler(Logger):
    """Continuous-batching decode service over a forward chain.

    ``max_slots`` — concurrent requests decoding per step;
    ``window`` — per-request length bound, ``prompt_len + steps <=
    window`` (default: the chain's positional table);
    ``max_queue`` — waiting-request cap beyond the slots (503 above);
    ``queue_timeout`` — default admission deadline in seconds (408
    for requests still queued past it);
    ``prefill_bucket`` — smallest compiled prefill width;
    ``kv`` / ``block_size`` / ``kv_blocks`` / ``prefill_chunk`` —
    paged-cache and chunked-prefill knobs (None defers to
    ``root.common.serving.*``; see the module docstring)."""

    def __init__(self, forwards, max_slots=4, window=None,
                 max_queue=32, queue_timeout=30.0, prefill_bucket=8,
                 kv=None, block_size=None, kv_blocks=None,
                 prefill_chunk=None, warm_buckets=None,
                 request_timeout=None, watchdog=None,
                 shed_block_factor=None):
        super(InferenceScheduler, self).__init__()
        if not serving_supported(forwards):
            raise ValueError(
                "chain cannot serve through the slot scheduler (needs "
                "causal cacheable blocks with apply_prefill/"
                "apply_step_slots; see serving_supported)")
        window = window or serving_window(forwards)
        if not window or int(window) < 2:
            raise ValueError(
                "no usable decode window: pass window= (the chain has "
                "no learned positional table to derive it from)")
        self.forwards = forwards
        self.max_slots = int(max_slots)
        self.window = int(window)
        self.max_queue = int(max_queue)
        self.queue_timeout = float(queue_timeout)
        self.prefill_bucket = int(prefill_bucket)
        kv = kv or _serving_conf("kv", "paged")
        if kv not in ("paged", "dense"):
            raise ValueError("kv must be 'paged' or 'dense'")
        if kv == "paged" and not paged_supported(forwards):
            self.info("chain has no paged decode step; falling back "
                      "to the dense slot cache")
            kv = "dense"
        self.kv = kv
        self.block_size = int(
            block_size or _serving_conf("block_size", 16))
        if self.block_size < 1:
            raise ValueError("block_size must be >= 1")
        self.blocks_per_slot = -(-self.window // self.block_size)
        if kv_blocks is None:
            kv_blocks = _serving_conf("kv_blocks", None)
        self.kv_blocks = int(
            kv_blocks or self.max_slots * self.blocks_per_slot) \
            if self.kv == "paged" else 0
        chunk = prefill_chunk if prefill_chunk is not None \
            else _serving_conf("prefill_chunk", 64)
        chunk = int(chunk or 0)
        if chunk and not chunked_supported(forwards):
            self.info("chain cannot prefill in chunks; long prompts "
                      "will prefill one-shot")
            chunk = 0
        #: chunk widths ride compiled executables — power-of-two
        self.prefill_chunk = _bucket(chunk, 1, 1 << 30) if chunk else 0
        self.warm_buckets = bool(
            _serving_conf("warm_buckets", True)
            if warm_buckets is None else warm_buckets)
        #: whole-request deadline default in seconds (0/None = none
        #: beyond the legacy queue_timeout) — per-submit overridable
        self.request_timeout = float(
            _serving_conf("request_timeout", 120.0)
            if request_timeout is None else request_timeout)
        #: stuck-decode-loop threshold (0 disables the watchdog)
        self.watchdog = float(_serving_conf("watchdog", 300.0)
                              if watchdog is None else watchdog)
        #: shed new submits once the queue's committed block budget
        #: exceeds factor x kv_blocks (0 disables; paged only)
        self.shed_block_factor = float(
            _serving_conf("shed_block_factor", 4.0)
            if shed_block_factor is None else shed_block_factor)
        self.stats = ServingMetrics()
        self._queue = collections.deque()
        self._active = {}            # slot -> _Request (decoding)
        self._prefilling = []        # admitted, mid-chunked-prefill
        self._admitting = []         # popped from queue, prefill in
        #                              progress this very iteration —
        #                              cancel() must still see them
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._closed = False
        self._draining = False
        self._drained = threading.Event()
        self._preempt_n = 0          # evictions the loop owes
        self._queued_blocks = 0      # block budget committed in-queue
        self._beat = None            # loop-iteration heartbeat stamp
        self._working = False        # loop mid-iteration (not parked)
        self._tripped_beat = None    # last beat the watchdog fired on
        self._thread = None
        self._watchdog_thread = None
        self._ready = threading.Event()
        self.cache_ = None           # set by the loop thread

    # -- client side ----------------------------------------------------

    def start(self):
        """Warm the device params (single-threaded — Array.devmem's
        lazy upload is not re-entrant), start the decode loop and
        block until it is READY — cache built and the paged-step
        bucket ladder compiled — so traffic never eats warmup
        compiles as decode stalls."""
        with self._lock:  # two racing start()s must not spawn two loops
            if self._thread is not None:
                started = True
            else:
                started = False
                self._thread = threading.Thread(
                    target=self._loop, daemon=True,
                    name="serving-scheduler")
        if started:
            self._ready.wait(600)
            return self
        try:
            for u in self.forwards:
                for arr in u.param_arrays().values():
                    arr.devmem
            self._thread.start()
        except BaseException:
            with self._lock:  # release the claim so start() can retry
                self._thread = None
            raise
        self._ready.wait(600)
        if self.watchdog > 0 and self._watchdog_thread is None:
            self._watchdog_thread = threading.Thread(
                target=self._watchdog_loop, daemon=True,
                name="serving-watchdog")
            self._watchdog_thread.start()
        return self

    def submit(self, prompt, steps, temperature=0.0, top_k=0,
               seed=None, stop_token=None, timeout=None):
        """Queue one sequence for decoding; returns a Future whose
        result is the full token list (prompt + generated, ending at
        the first generated stop token if one fired).  ``timeout``
        overrides the whole-request deadline (default
        ``request_timeout``; it covers queueing AND decoding — expiry
        mid-decode frees the slot/blocks and fails the future with
        :class:`DeadlineExceededError`).

        Raises ``ValueError`` on malformed requests (client errors),
        :class:`QueueFullError` when admission control rejects (queue
        depth, block-pressure shed, or :class:`DrainingError` once a
        drain began)."""
        prompt = [int(t) for t in prompt]
        steps = int(steps)
        if not prompt:
            raise ValueError("prompt must be non-empty")
        if steps < 1:
            raise ValueError("steps must be >= 1")
        if len(prompt) + steps > self.window:
            raise ValueError(
                "prompt_len + steps = %d exceeds the serving window "
                "(%d)" % (len(prompt) + steps, self.window))
        if self.kv == "paged":
            need = -(-(len(prompt) + steps) // self.block_size)
            if need > self.kv_blocks:
                raise ValueError(
                    "request needs %d KV blocks > pool capacity %d "
                    "(kv_blocks)" % (need, self.kv_blocks))
        temperature = float(temperature or 0.0)
        top_k = int(top_k or 0)
        if top_k and not temperature:
            raise ValueError(
                "top_k only applies to sampling — set temperature > 0")
        if seed is None:
            # unpinned sampling must draw fresh tokens per request
            seed = int.from_bytes(os.urandom(4), "little")
        ttl = float(timeout or self.request_timeout
                    or self.queue_timeout or 0)
        req = _Request(
            prompt, steps, temperature, top_k,
            int(stop_token) if stop_token is not None else None,
            int(seed) & 0xFFFFFFFF,
            time.monotonic() + ttl if ttl > 0 else None)
        need = self._blocks_for(req)
        with self._wake:
            if self._closed:
                raise SchedulerError("scheduler is closed")
            if self._draining:
                # rolling restart: this replica finishes what it has
                # and takes nothing new — callers retry elsewhere
                self.stats.record_reject(len(self._queue))
                raise DrainingError("scheduler is draining")
            if len(self._queue) >= self.max_queue:
                self.stats.record_reject(len(self._queue))
                raise QueueFullError(
                    "serving queue full (%d waiting)"
                    % len(self._queue))
            if self.kv == "paged" and self.shed_block_factor > 0 \
                    and self._queued_blocks + need \
                    > self.shed_block_factor * self.kv_blocks:
                # block-pressure shed: the queue already holds more
                # committed KV budget than the pool can turn over
                # soon — a deterministic 503 beats a guaranteed 408
                self.stats.record_shed(self._queued_blocks)
                raise QueueFullError(
                    "overloaded: %d KV blocks committed in-queue "
                    "(pool %d, shed factor %.1f)"
                    % (self._queued_blocks, self.kv_blocks,
                       self.shed_block_factor))
            self.stats.record_submit()
            self._queue.append(req)
            self._queued_blocks += need
            self._wake.notify()
        return req.future

    def _blocks_for(self, req):
        """The paged block budget a request commits (0 when dense)."""
        if self.kv != "paged":
            return 0
        return -(-(len(req.prompt) + req.steps) // self.block_size)

    def cancel(self, future, reason="cancelled by client"):
        """Cancel the request behind ``future`` (client disconnected
        or gave up): a queued request fails immediately; an in-flight
        one is reaped at the next chunk/decode boundary, returning its
        slot and KV blocks to the pool.  Returns True when the future
        belonged to this scheduler and was still unfinished."""
        victim = None
        with self._wake:
            for req in self._queue:
                if req.future is future:
                    self._queue.remove(req)
                    self._queued_blocks -= self._blocks_for(req)
                    victim = req
                    break
            else:
                for req in list(self._prefilling) \
                        + list(self._active.values()) \
                        + list(self._admitting):
                    if req.future is future:
                        req.cancelled = True
                        victim = req
                        self._wake.notify()
                        break
        if victim is None:
            return False
        if victim.slot is None and not victim.cancelled:
            # was queued: no device state to release — fail right here
            victim.fail(RequestCancelledError(reason))
            self.stats.record_cancel(len(victim.generated))
        return True

    def request_preempt(self, n=1):
        """Ask the loop to evict ``n`` active requests at the next
        decode boundary (youngest first): each victim's blocks return
        to the pool, its generated prefix is kept, and it requeues at
        the FRONT to resume via re-prefill — the mechanism priority
        scheduling builds on."""
        with self._wake:
            self._preempt_n += int(n)
            self._wake.notify()

    def drain(self, timeout=None):
        """Begin a graceful drain: admission closes (submits raise
        :class:`DrainingError` — 503 + Retry-After material), every
        queued and in-flight request runs to completion, then the
        ``drained`` event sets.  With ``timeout`` the call blocks for
        the drain to finish and returns whether it did; otherwise it
        returns immediately."""
        with self._wake:
            first = not self._draining
            self._draining = True
            if not (self._queue or self._active or self._prefilling):
                self._drained.set()
            self._wake.notify()
        if first:
            self.stats.record_drain()
            self.info("draining: admission closed, %d in flight",
                      self.in_flight)
        if timeout is not None:
            return self._drained.wait(timeout)
        return self._drained.is_set()

    @property
    def draining(self):
        return self._draining

    @property
    def drained(self):
        return self._drained.is_set()

    @property
    def in_flight(self):
        """Requests the scheduler still owes an answer (queued +
        prefilling + decoding)."""
        with self._lock:
            return len(self._queue) + len(self._prefilling) \
                + len(self._active) + len(self._admitting)

    def _kv_snapshot(self):
        out = {"kv_mode": self.kv,
               "prefill_chunk": self.prefill_chunk,
               "prefilling": len(self._prefilling)}
        cache = self.cache_
        if self.kv == "paged":
            out["kv_block_size"] = self.block_size
            out["kv_blocks_total"] = self.kv_blocks
            # the loop thread owns the free lists; these reads are
            # monitoring-grade (len() is atomic enough for a gauge)
            out["kv_blocks_used"] = \
                cache.used_blocks if cache is not None else 0
            out["kv_blocks_free"] = \
                cache.free_blocks if cache is not None \
                else self.kv_blocks
        return out

    def metrics(self):
        with self._lock:
            depth, active = len(self._queue), len(self._active)
            draining = self._draining
            queued_blocks = self._queued_blocks
        snap = self.stats.snapshot(queue_depth=depth,
                                   active_slots=active,
                                   max_slots=self.max_slots,
                                   kv=self._kv_snapshot())
        snap["window"] = self.window
        snap["draining"] = draining
        snap["drained"] = self._drained.is_set()
        snap["queued_kv_blocks"] = queued_blocks
        return snap

    def close(self):
        """Stop the loop, fail every unfinished request, and return
        every in-flight slot/block to the cache (a close with traffic
        in flight must not leak KV blocks — ``cache_.check()`` holds
        afterward)."""
        with self._wake:
            if self._closed:
                return
            self._closed = True
            self._wake.notify()
        loop_dead = True
        if self._thread is not None:
            self._thread.join(30)
            loop_dead = not self._thread.is_alive()
        err = SchedulerError("scheduler closed")
        with self._lock:
            pending = list(self._queue) + list(self._prefilling) \
                + list(self._active.values()) + list(self._admitting)
            self._queue.clear()
            self._prefilling = []
            self._active.clear()
            self._admitting = []
            self._queued_blocks = 0
        cache = self.cache_ if loop_dead else None
        for req in pending:
            if req.slot is not None and cache is not None:
                # the loop thread is dead (joined above): releasing
                # its cache bookkeeping from here cannot race it
                cache.release(req.slot)
                req.slot = None
            req.fail(err)
        if cache is not None:
            self._sync_kv_gauges(cache)
        self._drained.set()
        with self._lock:  # claim the watchdog before joining it
            wd, self._watchdog_thread = self._watchdog_thread, None
        if wd is not None:
            wd.join(5)

    # -- decode loop ----------------------------------------------------

    def _make_cache(self):
        if self.kv == "paged":
            return PagedKVCache(self.forwards, self.max_slots,
                                self.window,
                                block_size=self.block_size,
                                kv_blocks=self.kv_blocks)
        return SlotKVCache(self.forwards, self.max_slots, self.window)

    def _warm_paged(self, cache):
        """Compile the paged step's (occupancy, depth) bucket ladder
        BEFORE traffic: a bucket's first compile would otherwise land
        inside live serving as a multi-second decode stall (exactly
        the tail latency the buckets exist to remove).  The dummy
        batches are all padding rows — token 0 at position 0 through
        an all-zero block table, i.e. reads and writes confined to
        the reserved trash block."""
        buckets = sorted({_bucket(n, 1, self.max_slots)
                          for n in range(1, self.max_slots + 1)})
        depths = sorted({_bucket(n, 1, cache.blocks_per_slot)
                         for n in range(1, cache.blocks_per_slot + 1)})
        t0 = time.monotonic()
        for b in buckets:
            for t in depths:
                paged_decode_step(
                    self.forwards, cache,
                    numpy.zeros((b, 1), numpy.int32),
                    numpy.zeros((b,), numpy.int32),
                    numpy.zeros((b, t), numpy.int32),
                    numpy.zeros((b,), numpy.float32),
                    numpy.zeros((b,), numpy.int32),
                    numpy.zeros((b,), numpy.uint32),
                    numpy.zeros((b,), numpy.int32))
        self.info("paged-step warmup: %d occupancy x %d depth "
                  "buckets in %.2fs", len(buckets), len(depths),
                  time.monotonic() - t0)

    def _loop(self):
        try:
            cache = self._make_cache()
            if self.kv == "paged" and self.warm_buckets:
                self._warm_paged(cache)
            self.cache_ = cache
        except Exception as e:  # surface init failures to clients
            with self._wake:
                self._closed = True
                pending = list(self._queue)
                self._queue.clear()
            self._ready.set()
            for req in pending:
                req.future.set_exception(SchedulerError(repr(e)))
            raise
        self._ready.set()
        while True:
            with self._wake:
                self._working = False
                while not self._closed and not self._queue \
                        and not self._active and not self._prefilling \
                        and not self._preempt_n:
                    if self._draining:
                        self._drained.set()
                    self._wake.wait()
                if self._closed:
                    return
                # the watchdog measures from here: one iteration =
                # one reap + admit + chunk + decode step
                self._working = True
                self._beat = time.monotonic()
                self._expire_locked()
                admits = []
                while self._queue and cache.can_admit(
                        len(self._queue[0].prompt)
                        + self._queue[0].steps):
                    req = self._queue.popleft()
                    self._queued_blocks -= self._blocks_for(req)
                    req.slot = cache.alloc(len(req.prompt)
                                           + req.steps)
                    admits.append(req)
                    self._admitting.append(req)
            # jax work OUTSIDE the lock: submit() must never block on
            # a device step
            faults.fire("serving.scheduler.loop")
            self._reap(cache)
            self._do_preempts(cache)
            self._sync_kv_gauges(cache)
            for req in admits:
                self._begin_admit(req, cache)
                with self._lock:
                    self._admitting.remove(req)
            if self._prefilling:
                self._prefill_tick(cache)
            if self._active:
                self._step(cache)

    def _reap(self, cache):
        """Boundary sweep over the in-flight set: release the slot and
        blocks of every request that was cancelled, crossed its
        deadline mid-decode, or whose future a watchdog trip already
        failed — the other half of the deadline/disconnect contract
        (the future's error alone would still leak KV blocks)."""
        now = time.monotonic()
        with self._lock:
            flight = list(self._prefilling) \
                + list(self._active.values())
        for req in flight:
            if req.future.done():      # watchdog/cancel raced ahead
                self._drop_inflight(req, cache)
            elif req.cancelled:
                self._drop_inflight(req, cache)
                self.stats.record_cancel(len(req.generated))
                req.fail(RequestCancelledError(
                    "cancelled after %d generated tokens"
                    % len(req.generated)))
            elif req.deadline is not None and now > req.deadline:
                self._drop_inflight(req, cache)
                age_ms = (now - req.t_submit) * 1e3
                self.stats.record_expire(age_ms,
                                         tokens=len(req.generated))
                req.fail(DeadlineExceededError(
                    "deadline exceeded after %.0f ms (%d tokens "
                    "generated)" % (age_ms, len(req.generated)),
                    tokens_generated=len(req.generated)))

    def _drop_inflight(self, req, cache):
        """Remove one admitted request from the in-flight set and
        return its slot + blocks to the cache (loop thread only)."""
        with self._lock:
            if req in self._prefilling:
                self._prefilling.remove(req)
            self._active.pop(req.slot, None)
        if req.slot is not None:
            cache.release(req.slot)
            req.slot = None
        req.pf_seq = req.pf_caches = None
        self._sync_kv_gauges(cache)

    def _do_preempts(self, cache):
        """Evict owed preemptions at this decode boundary: youngest
        active request first (it loses the least re-prefill work and
        is what a priority scheduler would sacrifice for an older or
        higher-class request).  The victim keeps its generated prefix
        and requeues at the FRONT, so it resumes as soon as its own
        freed blocks (or better) are available."""
        while True:
            with self._lock:
                if not self._preempt_n:
                    return
                if not self._active:
                    self._preempt_n = 0  # demand dies with no targets
                    return
                self._preempt_n -= 1
                req = max(self._active.values(),
                          key=lambda r: (r.t_admit, r.slot))
                self._active.pop(req.slot, None)
            cache.release(req.slot)
            req.slot = None
            req.preempts += 1
            self.stats.record_preempt(len(req.generated))
            self._sync_kv_gauges(cache)
            with self._lock:
                self._queue.appendleft(req)
                self._queued_blocks += self._blocks_for(req)

    def _watchdog_loop(self):
        """Detect a stuck decode iteration and fail the pending
        futures — clients get a fast 5xx instead of a hung socket;
        when (if) the loop unsticks, :meth:`_reap` returns the
        zombies' slots and blocks to the pool."""
        period = max(0.02, min(1.0, self.watchdog / 8.0))
        while True:
            time.sleep(period)
            with self._lock:
                if self._closed:
                    return
                beat, working = self._beat, self._working
                tripped = self._tripped_beat
            if not working or beat is None or beat == tripped:
                continue
            stalled = time.monotonic() - beat
            if stalled <= self.watchdog:
                continue
            with self._lock:
                self._tripped_beat = beat
                victims = [r for r in list(self._queue)
                           + list(self._prefilling)
                           + list(self._active.values())
                           + list(self._admitting)
                           if not r.future.done()]
            err = SchedulerError(
                "decode loop stalled %.1fs (watchdog %.1fs) — "
                "request failed instead of hanging" % (stalled,
                                                       self.watchdog))
            for req in victims:
                req.fail(err)
            self.stats.record_watchdog_trip(len(victims), stalled)
            self.warning(
                "decode loop stalled %.1fs — failed %d pending "
                "requests", stalled, len(victims))

    def _sync_kv_gauges(self, cache):
        if self.kv == "paged":
            self.stats.set_kv_blocks(cache.used_blocks,
                                     cache.free_blocks)

    def _expire_locked(self):
        now = time.monotonic()
        kept = collections.deque()
        while self._queue:
            req = self._queue.popleft()
            if req.future.done():
                # a watchdog trip failed it while queued — drop it
                self._queued_blocks -= self._blocks_for(req)
            elif req.deadline is not None and now > req.deadline:
                self._queued_blocks -= self._blocks_for(req)
                queued_ms = (now - req.t_submit) * 1e3
                self.stats.record_expire(queued_ms,
                                         tokens=len(req.generated))
                req.fail(DeadlineExceededError(
                    "queued %.0f ms without a free slot" % queued_ms,
                    tokens_generated=len(req.generated)))
            else:
                kept.append(req)
        self._queue = kept

    def _staging_width(self, p_len, chunk):
        """Width of the batch-1 staging K/V row a prompt prefills
        into: the power-of-two bucket of the prompt, floored so it
        tiles both the chunk width and (paged) the block size."""
        bs = self.block_size if self.kv == "paged" else 1
        floor = max(self.prefill_bucket, bs, chunk or 1)
        return _bucket(p_len, floor, 1 << 30)

    def _begin_admit(self, req, cache):
        """Route one joining request: short sequences prefill
        one-shot; long ones start the chunked-prefill ride-along.  A
        preempted request resumes here — its prefill sequence is
        prompt + the kept generated prefix, so the re-prefill rebuilds
        exactly the K/V its decode steps had written before eviction."""
        req.t_admit = time.monotonic()
        seq = list(req.prompt) + list(req.generated)
        if req.preempts and req.generated:
            self.stats.record_resume(len(seq))
        req.pf_seq = seq
        p_len = len(seq)
        chunk = self.prefill_chunk
        if not chunk or p_len <= chunk:
            self._admit_oneshot(req, cache)
            return
        from veles_tpu import dtypes
        req.pf_chunk = chunk
        req.pf_width = self._staging_width(p_len, chunk)
        req.pf_off = 0
        try:
            req.pf_caches = {
                i: u.init_cache(1, req.pf_width,
                                dtypes.compute_dtype())
                for i, u in enumerate(self.forwards)
                if hasattr(u, "init_cache")}
        except Exception as e:
            self._retire(req, cache, error=e)
            return
        with self._lock:  # close() swaps the list under the same lock
            self._prefilling.append(req)

    def _admit_oneshot(self, req, cache):
        """Prefill one joining request's sequence (prompt, plus the
        generated prefix on resume) in a single compiled pass and emit
        its next token (the TTFT edge)."""
        p_len = len(req.pf_seq)
        width = self._staging_width(p_len, 0)
        # the SEQUENCE array stays inside the positional table; the
        # staging cache may be wider (insert trims it back)
        p_w = min(width, max(self.window, p_len))
        padded = numpy.zeros((1, p_w), numpy.int32)
        padded[0, :p_len] = req.pf_seq
        try:
            faults.fire("serving.scheduler.prefill")
            row_caches, last = prefill(
                self.forwards, padded, prompt_lens=[p_len],
                window=width)
        except Exception as e:
            self._retire(req, cache, error=e)
            return
        self._finish_admit(req, cache, row_caches, last)

    def _prefill_tick(self, cache):
        """Advance the oldest mid-prefill request by ONE chunk — the
        per-iteration decode-stall bound; the decode step for every
        in-flight stream runs right after, in the same iteration."""
        with self._lock:
            if not self._prefilling:  # reaped between check and tick
                return
            req = self._prefilling[0]
        p_len = len(req.pf_seq)
        c = req.pf_chunk
        off = req.pf_off
        end = min(off + c, p_len)
        clen = end - off
        padded = numpy.zeros((1, c), numpy.int32)
        padded[0, :clen] = req.pf_seq[off:end]
        kw = _bucket(off + c, c, req.pf_width)
        t0 = time.perf_counter()
        try:
            faults.fire("serving.scheduler.prefill")
            req.pf_caches, last = prefill_chunk(
                self.forwards, padded, off, [clen], req.pf_caches,
                key_width=kw)
        except Exception as e:
            with self._lock:
                if req in self._prefilling:
                    self._prefilling.remove(req)
            self._retire(req, cache, error=e)
            return
        self.stats.record_prefill_chunk(
            clen, (time.perf_counter() - t0) * 1e3)
        req.pf_off = end
        if end >= p_len:
            with self._lock:
                if req in self._prefilling:
                    self._prefilling.remove(req)
            self._finish_admit(req, cache, req.pf_caches, last)

    def _finish_admit(self, req, cache, row_caches, last):
        """Insert the prefilled staging row and emit the next token:
        draw 0 on a fresh admission, draw ``len(generated)`` on a
        preempt-resume — exactly the counter the decode step would
        have folded, so the resumed stream never forks."""
        try:
            cache.insert(req.slot, row_caches, len(req.pf_seq))
        except Exception as e:
            self._retire(req, cache, error=e)
            return
        req.pf_caches = None
        req.pf_seq = None
        tok = int(numpy.asarray(first_tokens(
            last, [req.temperature], [req.top_k], [req.seed],
            counts=[len(req.generated)]))[0])
        req.generated.append(tok)
        if req.t_first is None:  # TTFT is the FIRST first-token only
            req.t_first = time.monotonic()
            self.stats.record_first_token(
                (req.t_first - req.t_submit) * 1e3,
                (req.t_admit - req.t_submit) * 1e3)
        with self._lock:
            self._active[req.slot] = req
        self._maybe_finish(req, cache)

    def _step(self, cache):
        """Advance every active request one token through the shared
        compiled step, then retire finished ones at the boundary."""
        with self._lock:
            active = dict(self._active)
        if not active:
            return
        faults.fire("serving.scheduler.step")
        if self.kv == "paged":
            self._step_paged(cache, active)
        else:
            self._step_dense(cache, active)

    def _fill_row(self, arrays, j, req):
        toks, pos, temps, topks, seeds, counts = arrays
        toks[j, 0] = req.generated[-1]
        pos[j] = len(req.prompt) + len(req.generated) - 1
        temps[j] = req.temperature
        topks[j] = req.top_k
        seeds[j] = req.seed
        counts[j] = len(req.generated)

    def _step_paged(self, cache, active):
        """Packed step: ONLY the active slots ride the batch, padded
        to a power-of-two occupancy bucket; the attended range is the
        power-of-two block bucket of the deepest request."""
        slots = sorted(active)
        n = len(slots)
        b = _bucket(n, 1, self.max_slots)
        bs = cache.block_size
        deepest = max(len(active[s].prompt) + len(active[s].generated)
                      for s in slots)
        t = _bucket(-(-deepest // bs), 1, cache.blocks_per_slot)
        toks = numpy.zeros((b, 1), numpy.int32)
        pos = numpy.zeros((b,), numpy.int32)
        temps = numpy.zeros((b,), numpy.float32)
        topks = numpy.zeros((b,), numpy.int32)
        seeds = numpy.zeros((b,), numpy.uint32)
        counts = numpy.zeros((b,), numpy.int32)
        tables = numpy.zeros((b, t), numpy.int32)
        arrays = (toks, pos, temps, topks, seeds, counts)
        for j, slot in enumerate(slots):
            self._fill_row(arrays, j, active[slot])
        tables[:n] = cache.table_rows(slots, t)
        nxt = numpy.asarray(paged_decode_step(
            self.forwards, cache, toks, pos, tables, temps, topks,
            seeds, counts))
        self.stats.record_step(n, b)
        for j, slot in enumerate(slots):
            req = active[slot]
            req.generated.append(int(nxt[j]))
            self._maybe_finish(req, cache)

    def _step_dense(self, cache, active):
        """Legacy full-batch step: free slots decode garbage rows."""
        s = self.max_slots
        toks = numpy.zeros((s, 1), numpy.int32)
        pos = numpy.zeros((s,), numpy.int32)
        temps = numpy.zeros((s,), numpy.float32)
        topks = numpy.zeros((s,), numpy.int32)
        seeds = numpy.zeros((s,), numpy.uint32)
        counts = numpy.zeros((s,), numpy.int32)
        arrays = (toks, pos, temps, topks, seeds, counts)
        for slot, req in active.items():
            self._fill_row(arrays, slot, req)
        nxt = numpy.asarray(slot_decode_step(
            self.forwards, cache, toks, pos, temps, topks, seeds,
            counts))
        self.stats.record_step(len(active), s)
        for slot, req in active.items():
            req.generated.append(int(nxt[slot]))
            self._maybe_finish(req, cache)

    def _maybe_finish(self, req, cache, error=None):
        done = error is not None \
            or len(req.generated) >= req.steps \
            or (req.stop_token is not None
                and req.generated[-1] == req.stop_token)
        if done:
            self._retire(req, cache, error=error)

    def _retire(self, req, cache, error=None):
        with self._lock:
            self._active.pop(req.slot, None)
        if req.slot is not None:
            cache.release(req.slot)
            req.slot = None
        self._sync_kv_gauges(cache)
        if error is not None:
            req.fail(error if isinstance(error, SchedulerError)
                     else SchedulerError(repr(error)))
            return
        if req.future.done():
            # watchdog/cancel failed it first — the tokens are moot
            return
        now = time.monotonic()
        self.stats.record_complete(
            len(req.generated), now - req.t_submit,
            (req.t_first - req.t_submit) * 1e3,
            (req.t_admit - req.t_submit) * 1e3)
        try:
            req.future.set_result(list(req.prompt) + req.generated)
        except concurrent.futures.InvalidStateError:
            pass
