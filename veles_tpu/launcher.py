"""Launcher — the composition root (rebuild of veles/launcher.py:100-906).

Owns runtime mode (standalone / coordinator / worker), the device, and
the workflow lifecycle.  The reference parked the main thread in a
Twisted reactor; here standalone runs are a plain synchronous
``workflow.run()`` (the scheduler's worklist already expresses the
graph's control flow) and distributed modes host the asyncio
coordinator/worker services from :mod:`veles_tpu.parallel.coordinator`.
"""

import json
import resource
import time

from veles_tpu.backends import Device
from veles_tpu.logger import Logger
from veles_tpu.memory import Watcher


class Launcher(Logger):
    """ref: veles/launcher.py:100.  Mode detection per launcher.py:333-356:
    ``listen`` → coordinator ("master"), ``master_address`` → worker
    ("slave"), else standalone."""

    def __init__(self, backend=None, device_index=0, listen=None,
                 master_address=None, graphics=None, status_url=None,
                 profile_dir=None, workers=None, worker_cmd_tail=None,
                 **kwargs):
        super(Launcher, self).__init__()
        self._listen = listen
        self._master_address = master_address
        self._backend = backend
        self._device_index = device_index
        self._graphics = graphics
        self._status_url = status_url
        self.device = None
        self.workflow = None
        self.start_time = None
        self.stopped = False
        self.coordinator = None
        self.graphics_server = None
        self.status_notifier = None
        self._profile_dir = profile_dir
        self._profiling = False
        #: worker specs: int (N local), or list/comma-list of host specs
        #: ("localhost" → subprocess, anything else → ssh, ref:
        #: veles/launcher.py:617-842 SSH slave spawn)
        self._workers = workers
        #: the re-exec tail (workflow file, config, -c overrides…) the
        #: CLI assembled for spawned workers
        self._worker_cmd_tail = list(worker_cmd_tail or [])
        self._worker_procs = []

    # -- mode (ref: launcher.py:333-356) --------------------------------------

    @property
    def mode(self):
        if self._listen:
            return "master"
        if self._master_address:
            return "slave"
        return "standalone"

    @property
    def is_standalone(self):
        return self.mode == "standalone"

    @property
    def is_master(self):
        return self.mode == "master"

    @property
    def is_slave(self):
        return self.mode == "slave"

    # -- lifecycle (ref: launcher.py:431-579) ---------------------------------

    def add_ref(self, workflow):
        """Called by the top-level Workflow adopting this launcher as its
        parent."""
        self.workflow = workflow

    def del_ref(self, workflow):
        if self.workflow is workflow:
            self.workflow = None

    def initialize(self, **kwargs):
        from veles_tpu.config import root
        # join the multi-host gang first (no-op unless VELES_TPU_
        # COORDINATOR/NUM_PROCESSES/PROCESS_ID configure one; pod
        # auto-detection needs multihost.initialize(auto=True)) — must
        # precede the first JAX use
        from veles_tpu.parallel import multihost
        pid, nproc = multihost.initialize()
        if nproc > 1:
            self.info("multi-host gang: process %d/%d", pid, nproc)
        if self.device is None:
            self.device = Device(backend=self._backend,
                                 device_index=self._device_index)
        self.info("mode: %s, device: %s", self.mode, self.device)
        # graphics PUB fan-out (ref: launcher starting the graphics
        # server process, veles/launcher.py:431-548); client processes
        # attach with `python -m veles_tpu.graphics_client <endpoint>`
        graphics = self._graphics
        if graphics is None:
            graphics = root.common.graphics.get("enabled", False)
        if graphics and not self.is_slave:
            from veles_tpu.graphics_server import GraphicsServer
            self.graphics_server = GraphicsServer(
                port=int(root.common.graphics.get("port", 0)))
        self.workflow.initialize(device=self.device, **kwargs)

    def run(self):
        """Run to completion (standalone) or serve (distributed)."""
        from veles_tpu.config import root
        self.start_time = time.time()
        status_url = self._status_url \
            or root.common.web.get("status_url")
        if status_url and not self.is_slave:
            from veles_tpu.web_status import StatusNotifier
            self.status_notifier = StatusNotifier(status_url, self)
            self.status_notifier.start()
        if self._profile_dir:
            # device-level trace of the whole run (SURVEY.md §5: the
            # fused programs need jax.profiler, not host wall timers);
            # per-unit TraceAnnotations ride root.common.trace.run
            import jax.profiler
            root.common.trace.run = True
            jax.profiler.start_trace(self._profile_dir)
            self._profiling = True
            self.info("jax.profiler trace -> %s", self._profile_dir)
        try:
            if self.is_standalone:
                self.workflow.run()
            elif self.is_master:
                if self._workers:
                    self._spawn_workers()
                from veles_tpu.parallel.coordinator import serve_master
                serve_master(self)
            else:
                from veles_tpu.parallel.coordinator import serve_worker
                serve_worker(self)
        finally:
            self.stop()

    # -- worker spawning (ref: veles/launcher.py:617-842) ---------------------

    def _spawn_workers(self):
        import shlex
        import socket
        import subprocess
        import sys
        import tempfile
        specs = self._workers
        if isinstance(specs, int):
            specs = ["localhost"] * specs
        elif isinstance(specs, str):
            specs = [s for s in specs.split(",") if s]
        host, _, port = (self._listen or ":5050").rpartition(":")
        port = port or "5050"
        if port == "0":
            # spawned workers need a dialable address before the
            # coordinator binds — an OS-assigned port can't be forwarded
            # to them
            raise ValueError(
                "-l :0 (OS-assigned port) cannot be combined with -w "
                "worker spawning; pick a fixed port")
        n_local_devices = len(self.device.jax_devices) \
            if self.device is not None else 1
        local_count = 0
        for i, spec in enumerate(specs):
            # "host/D" pins the worker to device D (ref: veles -n
            # host/0:0x3 device syntax); plain local workers round-robin
            # over this host's devices
            spec, _, dev = spec.partition("/")
            is_local = spec in ("localhost", "127.0.0.1", "")
            if not dev:
                dev = str(local_count % n_local_devices) if is_local \
                    else "0"
            tail = list(self._worker_cmd_tail) + ["-d", dev]
            if is_local:
                tail += ["-m", "%s:%s" % (host or "127.0.0.1", port)]
                cmd = [sys.executable, "-m", "veles_tpu"] + tail
                local_count += 1
            else:
                # a remote worker must dial THIS host, not its own
                # loopback; quote every arg — ssh re-joins argv through
                # the remote shell
                master_host = host if host not in ("", "0.0.0.0") \
                    else socket.getfqdn()
                tail += ["-m", "%s:%s" % (master_host, port)]
                cmd = ["ssh", "-o", "BatchMode=yes", spec,
                       "python3", "-m", "veles_tpu"] + [
                           shlex.quote(a) for a in tail]
            log = tempfile.NamedTemporaryFile(
                mode="wb", suffix=".log", prefix="veles_worker%d_" % i,
                delete=False)
            proc = subprocess.Popen(cmd, stdout=log, stderr=log)
            self._worker_procs.append((proc, log.name))
            self.info("spawned worker %d on %s dev %s (pid %d, log %s)",
                      i, spec or "localhost", dev, proc.pid, log.name)

    def _reap_workers(self, timeout=30.0):
        import subprocess
        for proc, log in self._worker_procs:
            try:
                rc = proc.wait(timeout)
            except subprocess.TimeoutExpired:
                proc.kill()
                rc = proc.wait(5)
            if rc:
                try:
                    with open(log, "rb") as f:
                        tail = f.read()[-500:].decode(errors="replace")
                except OSError:
                    tail = "<no log>"
                self.warning("worker pid %d exited rc=%d: %s",
                             proc.pid, rc, tail)
        self._worker_procs = []

    def boot(self, **kwargs):
        self.initialize(**kwargs)
        self.run()

    def stop(self):
        if self.stopped:
            return
        self.stopped = True
        if self._worker_procs:
            self._reap_workers()
        if self._profiling:
            import jax.profiler
            jax.profiler.stop_trace()
            self._profiling = False
        if self.status_notifier is not None:
            self.status_notifier.stop()
        if self.graphics_server is not None:
            self.graphics_server.close()
        elapsed = time.time() - (self.start_time or time.time())
        self.workflow.stop()
        self.workflow.print_stats()
        used, peak = Watcher.report()
        rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        self.info("total run time: %.2fs; peak RSS: %.1f MiB; "
                  "peak device mem: %.1f MiB",
                  elapsed, rss / 1024.0, peak / 2 ** 20)

    # -- results (ref: workflow.py:827-849 + --result-file) -------------------

    def write_results(self, path):
        metrics = self.workflow.gather_results()
        metrics["elapsed_sec"] = time.time() - (self.start_time
                                                or time.time())
        # the ensemble aggregator needs to find each instance's snapshot
        # (ref: ensemble/base_workflow.py reads them back for test mode)
        from veles_tpu.snapshotter import SnapshotterBase
        for u in self.workflow.units:
            if isinstance(u, SnapshotterBase) \
                    and getattr(u, "destination", None):
                metrics["Snapshot"] = u.destination
        with open(path, "w") as f:
            json.dump(metrics, f, indent=2, default=str)
        self.info("results -> %s", path)
        return metrics
