"""Unit — the dataflow graph node.

Rebuild of veles/units.py (IUnit/Unit, ref: units.py:59-913).  A model in
this framework is a :class:`~veles_tpu.workflow.Workflow`: a directed
graph of Units wired by control links (:meth:`Unit.link_from`) and data
links (:meth:`Unit.link_attrs`).  Control flow is event-driven through
*gates*: a unit runs when all of its incoming links have fired, unless its
``gate_block`` Bool is set; ``gate_skip`` propagates the signal without
running (ref: units.py:524-552).

TPU-first scheduling decision: the reference walked the graph on a Twisted
thread pool (units.py:485-505) because each unit dispatched its own GPU
kernels and Python-level overlap mattered.  Here the heavy compute of a
workflow segment is fused into **one jitted XLA program**
(:mod:`veles_tpu.accelerated_units`), XLA dispatch is already async, and
the host-side walk is microseconds — so the scheduler is a deterministic
worklist run by the Workflow (no thread pool, no per-unit locks in the hot
path, no re-entrancy hazards).  Service units that genuinely need threads
(plotting, web status) manage their own.
"""

import time

from veles_tpu.mutable import Bool, LinkableAttribute
from veles_tpu.unit_registry import RegisteredDistributable


def _unit_metrics():
    """The shared per-unit telemetry series (created on first use so
    importing units never forces the registry into being)."""
    from veles_tpu.telemetry import metrics
    return (
        metrics.histogram(
            "veles_unit_run_seconds",
            "wall time of one unit run() firing", ("unit",)),
        metrics.histogram(
            "veles_unit_gate_wait_seconds",
            "time between a unit's first incoming link firing and its "
            "gate opening (scheduling slack on multi-input units)",
            ("unit",)),
        metrics.counter(
            "veles_unit_runs_total", "unit run() firings", ("unit",)),
    )


class MissingDemand(AttributeError):
    """A demanded attribute is absent at initialize() time — the workflow
    re-queues the unit and tries again after its suppliers initialize
    (ref: veles/units.py:682, workflow.py:319-341)."""

    def __init__(self, unit, attrs):
        super(MissingDemand, self).__init__(
            "%s demands unsatisfied attribute(s): %s" %
            (unit, ", ".join(sorted(attrs))))
        self.unit = unit
        self.attrs = attrs


class Unit(RegisteredDistributable):
    """A graph node with gates, links and a lifecycle
    (ref: veles/units.py:108).

    Lifecycle: ``__init__`` (wire the graph) → ``initialize`` (allocate,
    validate demands) → ``run`` (once per gate opening) → ``stop``.
    """

    hide_from_registry = True

    def __init__(self, workflow, name=None, view_group=None, **kwargs):
        super(Unit, self).__init__()
        self._name = name
        self.view_group = view_group or getattr(self, "VIEW_GROUP", "PLUMBING")
        self.links_from = {}   # src Unit -> fired flag (bool)
        self.links_to = {}     # dst Unit -> True (ordered set)
        self.gate_block = Bool(False, "gate_block")
        self.gate_skip = Bool(False, "gate_skip")
        self._demanded = set()
        self._is_initialized = False
        self.timers = {"run": 0.0, "runs": 0}
        self._workflow = None
        if workflow is not None:
            self.workflow = workflow

    def init_unpickled(self):
        super(Unit, self).init_unpickled()
        self._gate_wait_t0_ = None
        self._gate_wait_ = 0.0
        self._telemetry_ = None

    # -- identity ----------------------------------------------------------

    @property
    def name(self):
        return self._name or type(self).__name__

    @name.setter
    def name(self, value):
        self._name = value

    @property
    def id(self):
        return type(self).__id__

    def __repr__(self):
        return "<%s \"%s\">" % (type(self).__name__, self.name)

    # -- workflow membership ----------------------------------------------

    @property
    def workflow(self):
        return self._workflow

    @workflow.setter
    def workflow(self, wf):
        if self._workflow is not None:
            self._workflow.del_ref(self)
        self._workflow = wf
        wf.add_ref(self)

    @property
    def is_standalone(self):
        return self._workflow.is_standalone if self._workflow else True

    @property
    def is_master(self):
        return self._workflow.is_master if self._workflow else False

    @property
    def is_slave(self):
        return self._workflow.is_slave if self._workflow else False

    # -- graph wiring (ref: units.py:554-680) -------------------------------

    def link_from(self, *units):
        """Add control edges ``unit → self``; self runs after all fire."""
        for src in units:
            self.links_from[src] = False
            src.links_to[self] = True
        return self

    def unlink_from(self, *units):
        for src in units:
            self.links_from.pop(src, None)
            src.links_to.pop(self, None)
        return self

    def unlink_all(self):
        self.unlink_before()
        self.unlink_after()

    def unlink_before(self):
        for src in list(self.links_from):
            self.unlink_from(src)

    def unlink_after(self):
        for dst in list(self.links_to):
            dst.unlink_from(self)

    def link_attrs(self, other, *args, two_way=False):
        """Data links: each arg is ``"attr"`` (same name both sides) or
        ``("own_name", "other_name")`` (ref: veles/units.py:638)."""
        for arg in args:
            if isinstance(arg, str):
                own, theirs = arg, arg
            else:
                own, theirs = arg
            LinkableAttribute(self, own, (other, theirs), two_way=two_way)
        return self

    def demand(self, *attrs):
        """Declare attributes that must be non-None before initialize
        (ref: veles/units.py:682)."""
        self._demanded.update(attrs)

    # -- lifecycle ----------------------------------------------------------

    def verify_demands(self):
        missing = {a for a in self._demanded
                   if getattr(self, a, None) is None}
        if missing:
            raise MissingDemand(self, missing)

    def initialize(self, **kwargs):
        """Validate demands and allocate.  Subclasses call super() first."""
        self.verify_demands()
        self._is_initialized = True

    @property
    def is_initialized(self):
        return self._is_initialized

    def run(self):
        """One firing of this unit.  Subclasses override."""
        pass

    def stop(self):
        """Called on workflow shutdown; release external resources."""
        pass

    # -- gate machinery (ref: units.py:524-552, 782-803) --------------------

    def open_gate(self, src):
        """Mark the ``src → self`` edge fired; True when all inputs fired
        (flags then reset for the next wave).  On multi-input units the
        span between the FIRST edge firing and the gate opening is the
        unit's gate-wait (scheduling slack), surfaced through telemetry."""
        if src is not None and src in self.links_from:
            if len(self.links_from) > 1 and self._gate_wait_t0_ is None \
                    and not any(self.links_from.values()):
                # fallback stamp for signals that bypassed
                # run_dependent (direct open_gate callers)
                self._gate_wait_t0_ = time.time()
            self.links_from[src] = True
        if all(self.links_from.values()) or not self.links_from:
            for k in self.links_from:
                self.links_from[k] = False
            t0 = self._gate_wait_t0_
            self._gate_wait_ = time.time() - t0 if t0 else 0.0
            self._gate_wait_t0_ = None
            return True
        return False

    def _check_gate_and_run(self, src):
        """Scheduler entry: signal arriving over the ``src → self`` edge."""
        if self.gate_block:
            return
        if not self.open_gate(src):
            return
        if not self.gate_skip:
            if self._workflow is not None and self._workflow.stopped:
                return
            self._run_wrapped()
        self.run_dependent()

    def _run_wrapped(self):
        """run() with timing + initialization check
        (ref: units.py:805-845).  Under ``root.common.trace.run`` each
        run is additionally a jax.profiler TraceAnnotation, so per-unit
        spans appear inside the device trace — the fused XLA programs
        make host wall-timers blind to where device time goes
        (SURVEY.md §5 jax.profiler requirement)."""
        if not self._is_initialized:
            raise RuntimeError("%s.run() before initialize()" % self)
        import veles_tpu.telemetry as telemetry
        from veles_tpu.config import root
        from veles_tpu.logger import events
        tracing = root.common.trace.get("run")
        observing = telemetry.enabled()
        gate_wait = self._gate_wait_
        self._gate_wait_ = 0.0
        span_id = None
        if observing:
            span_id = telemetry.next_span_id()
            events.record("unit:%s" % self.name, "begin",
                          unit=self.name, cls=type(self).__name__,
                          span=span_id)
        t0 = time.time()
        error = None
        try:
            if tracing:
                import jax.profiler
                with jax.profiler.TraceAnnotation(
                        "unit:%s" % self.name):
                    self.run()
            else:
                self.run()
        except BaseException as e:
            # the end span names the exception type so the flight
            # recorder's event tail shows WHICH unit died, not just
            # that the wave stopped
            error = type(e).__name__
            raise
        finally:
            dt = time.time() - t0
            self.timers["run"] += dt
            self.timers["runs"] += 1
            if observing:
                end_attrs = {"unit": self.name,
                             "cls": type(self).__name__,
                             "span": span_id, "duration": dt,
                             "gate_wait": round(gate_wait, 6)}
                if error is not None:
                    end_attrs["error"] = error
                events.record("unit:%s" % self.name, "end",
                              **end_attrs)
                if self._telemetry_ is None:
                    run_h, wait_h, runs_c = _unit_metrics()
                    self._telemetry_ = (run_h.labels(self.name),
                                        wait_h.labels(self.name),
                                        runs_c.labels(self.name))
                run_h, wait_h, runs_c = self._telemetry_
                run_h.observe(dt)
                runs_c.inc()
                if gate_wait:
                    wait_h.observe(gate_wait)
            if root.common.get("timings"):
                self.debug("%s ran in %.4fs", self.name, dt)

    def run_dependent(self):
        """Propagate the control signal to successors
        (ref: units.py:485-505) — enqueues on the workflow scheduler.
        A multi-input successor's gate-wait clock starts when its FIRST
        producer finishes (here, at schedule time — not at queue
        delivery, which the serial worklist makes back-to-back)."""
        now = time.time()
        for dst in self.links_to:
            if len(dst.links_from) > 1 and dst._gate_wait_t0_ is None \
                    and not any(dst.links_from.values()):
                dst._gate_wait_t0_ = now
            self._workflow.schedule(dst, self)

    # -- export metadata ----------------------------------------------------

    def export_config(self):
        """Picklable kwargs snapshot for package_export (overridden by
        units with meaningful config)."""
        return {}
