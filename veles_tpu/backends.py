"""Device backends — TPU-first rebuild of veles/backends.py.

The reference ran a runtime registry of OpenCL/CUDA/numpy devices with
``Device.__new__`` dispatch and an ``AutoDevice`` priority scheme
(ref: veles/backends.py:166-197, 406-424).  Here the registry survives —
it is the product's ``-a/--backend`` surface — but the devices wrap JAX:

- :class:`TPUDevice` — one or more TPU chips, plus the
  :class:`~jax.sharding.Mesh` factory used by the parallel layer.
- :class:`NumpyDevice` — the JAX CPU backend (keeps the reference's name:
  it is the "plain host" fallback, ref: backends.py:918-948); with
  ``XLA_FLAGS=--xla_force_host_platform_device_count=N`` it exposes N
  virtual devices, which is how multi-chip sharding is tested off-TPU.
- :class:`AutoDevice` — priority pick (tpu 30 > gpu 20 > cpu 10; ref:
  backends.py:406-424's cuda 30 > ocl 20 > numpy 10 ladder).

Per-device autotuned block sizes (ref: backends.py:623-731) are XLA's job
now; what survives is the *device benchmark* ("computing power") used by
the elastic coordinator to weight job distribution — see
:meth:`Device.compute_power` (ref: veles/accelerated_units.py:706-824).
"""

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy

from veles_tpu.config import root
from veles_tpu.logger import Logger


class BackendRegistry(type):
    """Metaclass registry of Device classes keyed by ``BACKEND``
    (ref: veles/backends.py:166-180)."""

    backends = {}

    def __init__(cls, name, bases, namespace):
        super(BackendRegistry, cls).__init__(name, bases, namespace)
        backend = namespace.get("BACKEND")
        if backend is not None:
            BackendRegistry.backends[backend] = cls


class Device(Logger, metaclass=BackendRegistry):
    """Base device.  ``Device()`` (or ``Device(backend="auto")``) dispatches
    through the registry like the reference's ``Device.__new__``
    (ref: veles/backends.py:190-197); ``backend="tpu"|"cpu"|"numpy"``
    forces one.
    """

    BACKEND = None
    PRIORITY = 0

    def __new__(cls, *args, **kwargs):
        if cls is not Device:
            return super(Device, cls).__new__(cls)
        # explicit argument wins; the env var was already folded into
        # root.common.engine.backend at config-import time
        backend = (args[0] if args else None) or kwargs.get("backend") \
            or root.common.engine.get("backend", "auto")
        target = BackendRegistry.backends.get(backend, AutoDevice)
        if target is AutoDevice:
            target = AutoDevice.pick()
        return super(Device, cls).__new__(target)

    def __init__(self, backend=None, device_index=0, **kwargs):
        super(Device, self).__init__()
        self._power_ = None
        self.device_index = device_index
        self._jax_devices_ = self._discover()
        if not self._jax_devices_:
            raise RuntimeError(
                "no %s devices available" % (self.BACKEND or "jax"))

    def __reduce__(self):
        # devices are runtime context: snapshots store (backend, index)
        # and reconstruct a live handle at load (the reference re-created
        # devices on resume too, veles/__main__.py:604-616)
        return (Device, (self.BACKEND, self.device_index))

    # -- discovery (subclasses) --------------------------------------------

    _PLATFORM = None

    def _discover(self):
        # a Device owns only THIS process's chips (in a multi-host gang
        # device_put to another host's device is invalid); global
        # placement goes through parallel.sharding.put over a mesh
        # spanning jax.devices()
        try:
            return list(jax.local_devices(backend=self._PLATFORM))
        except RuntimeError:
            return []

    @classmethod
    def available(cls):
        try:
            return bool(jax.devices(cls._PLATFORM))
        except RuntimeError:
            return False

    # -- surface ------------------------------------------------------------

    @property
    def jax_device(self):
        """The primary jax.Device addressed by this Device object."""
        return self._jax_devices_[self.device_index]

    @property
    def jax_devices(self):
        """All local devices of this backend (mesh building blocks)."""
        return list(self._jax_devices_)

    @property
    def backend_name(self):
        return self.BACKEND

    def __repr__(self):
        return "<%s %s (%d device(s))>" % (
            type(self).__name__, self.jax_device, len(self._jax_devices_))

    def sync(self):
        """Block until all queued work on this device is done (the
        reference's ``--sync-run`` queue flush,
        ref: veles/accelerated_units.py:292-295)."""
        jnp.zeros((), device=self.jax_device).block_until_ready()

    def make_mesh(self, axis_shapes):
        """Build a :class:`jax.sharding.Mesh` over this backend's devices.

        ``axis_shapes`` is an ordered dict/list of ``(axis_name, size)``.
        This is the bridge into :mod:`veles_tpu.parallel`.
        """
        from veles_tpu.parallel.mesh import build_mesh
        return build_mesh(dict(axis_shapes), devices=self.jax_devices)

    # -- memory accounting ---------------------------------------------------

    def memory_stats(self):
        """Live device memory stats where the platform reports them."""
        try:
            return self.jax_device.memory_stats() or {}
        except Exception:
            return {}

    # -- computing power (ref: veles/accelerated_units.py:706-824) ----------

    BENCHMARK_N = 2048

    def compute_power(self, refresh=False):
        """GEMM roofline probe → ops/sec rating, cached on disk per device
        kind (the reference persisted per-device dicts as JSON,
        ref: veles/backends.py:623-731).  The elastic coordinator uses the
        rating to weight job distribution exactly like the reference's
        slave "power" handshake field (ref: veles/server.py:540-567).
        """
        if self._power_ is not None and not refresh:
            return self._power_
        cache_dir = root.common.dirs.get("cache", ".")
        key = "%s-%s" % (self.jax_device.platform, self.jax_device.device_kind)
        key = key.replace(" ", "_").replace("/", "_")
        cache_file = os.path.join(cache_dir, "device_power.json")
        powers = {}
        if os.path.isfile(cache_file):
            try:
                with open(cache_file) as f:
                    powers = json.load(f)
            except (ValueError, OSError):
                powers = {}
        if not refresh and key in powers:
            self._power_ = powers[key]
            return self._power_
        n = self.BENCHMARK_N
        x = jnp.ones((n, n), dtype=jnp.bfloat16, device=self.jax_device)

        @jax.jit
        def gemm(a, b):
            return a @ b

        gemm(x, x).block_until_ready()  # compile + warm
        t0 = time.perf_counter()
        reps = 8
        out = x
        for _ in range(reps):
            out = gemm(out, x)
        out.block_until_ready()
        dt = (time.perf_counter() - t0) / reps
        self._power_ = float(2 * n ** 3 / dt)  # FLOP/s
        powers[key] = self._power_
        try:
            os.makedirs(cache_dir, exist_ok=True)
            with open(cache_file, "w") as f:
                json.dump(powers, f)
        except OSError:
            pass
        self.info("device %s computing power: %.1f GFLOP/s",
                  key, self._power_ / 1e9)
        return self._power_


class TPUDevice(Device):
    """TPU chip(s) via JAX (ref role: veles/backends.py:745 CUDADevice)."""

    BACKEND = "tpu"
    PRIORITY = 30
    _PLATFORM = "tpu"


class GPUDevice(Device):
    """GPU via JAX, when present (keeps the registry honest on non-TPU
    boxes; ref role: veles/backends.py:426 OpenCLDevice)."""

    BACKEND = "gpu"
    PRIORITY = 20
    _PLATFORM = "gpu"


class NumpyDevice(Device):
    """Host CPU backend (ref: veles/backends.py:918-948).  With
    ``--xla_force_host_platform_device_count=N`` this is the multi-chip
    simulation substrate for tests."""

    BACKEND = "numpy"
    PRIORITY = 10
    _PLATFORM = "cpu"


# "cpu" is an alias for numpy in the registry.
class _CPUAlias(NumpyDevice):
    BACKEND = "cpu"


class AutoDevice(Device):
    """Priority-based automatic backend pick
    (ref: veles/backends.py:406-424)."""

    BACKEND = "auto"

    @staticmethod
    def pick():
        ranked = sorted(
            {c for c in BackendRegistry.backends.values()
             if c not in (AutoDevice, Device) and c.PRIORITY > 0},
            key=lambda c: -c.PRIORITY)
        for cls in ranked:
            if cls.available():
                return cls
        raise RuntimeError("no JAX backend available")
