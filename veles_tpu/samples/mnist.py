"""MNIST fully-connected workflow — BASELINE.json config 1
(znicz MnistWorkflow 784→100→10, SGD; ref surface:
manualrst_veles_algorithms.rst "MnistSimple").

Run: ``python -m veles_tpu veles_tpu/samples/mnist.py \
veles_tpu/samples/mnist_config.py``

Graph::

    start → repeater → loader → trainer(gd) → decision ─┬→ repeater (loop)
                                                        ├→ snapshotter
                                                        └→ end  [gated on
                                                            decision.complete]
"""

import gzip
import os
import struct

import numpy

from veles_tpu.config import root
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.models.standard import StandardWorkflow


def _read_idx(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">HBB", f.read(4))
        dtype_code, ndim = magic[1], magic[2]
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        assert dtype_code == 0x08  # ubyte
        return numpy.frombuffer(f.read(), numpy.uint8).reshape(dims)


class MnistLoader(FullBatchLoader):
    """Standard IDX files from ``root.common.dirs.datasets``/mnist; a
    deterministic synthetic stand-in is generated when the files are
    absent (this build environment has no egress — the reference's
    Downloader unit would have fetched them, veles/downloader.py:56)."""

    def _find(self, *names):
        base = os.path.join(root.common.dirs.get("datasets", "data"),
                            "mnist")
        for n in names:
            for suffix in ("", ".gz"):
                p = os.path.join(base, n + suffix)
                if os.path.isfile(p):
                    return p
        return None

    def load_data(self):
        ti = self._find("train-images-idx3-ubyte", "train-images.idx3-ubyte")
        tl = self._find("train-labels-idx1-ubyte", "train-labels.idx1-ubyte")
        vi = self._find("t10k-images-idx3-ubyte", "t10k-images.idx3-ubyte")
        vl = self._find("t10k-labels-idx1-ubyte", "t10k-labels.idx1-ubyte")
        if all((ti, tl, vi, vl)):
            train = _read_idx(ti).reshape(-1, 784)
            train_l = _read_idx(tl)
            valid = _read_idx(vi).reshape(-1, 784)
            valid_l = _read_idx(vl)
            self.info("loaded real MNIST (%d train / %d validation)",
                      len(train), len(valid))
        else:
            n_train = int(root.mnist_tpu.get("synthetic_train", 8192))
            n_valid = int(root.mnist_tpu.get("synthetic_valid", 1024))
            kind = root.mnist_tpu.get("synthetic_kind", "blobs")
            self.warning("MNIST files not found under %s — generating a "
                         "deterministic synthetic stand-in (%s)",
                         root.common.dirs.get("datasets", "data"), kind)
            if kind == "glyphs":
                # the quality surrogate: procedurally rendered digits of
                # MNIST-matched difficulty (veles_tpu/datasets/glyphs.py)
                from veles_tpu.datasets import render_digits
                imgs, tl_all = render_digits(n_train + n_valid,
                                             seed=1234)
                data = imgs.reshape(len(imgs), 784) * 255.0
            else:
                # Gaussian class blobs: a fast mechanics-proof task
                rng = numpy.random.default_rng(1234)
                centers = rng.normal(scale=2.0, size=(10, 784))
                tl_all = rng.integers(0, 10, n_train + n_valid)
                data = (centers[tl_all]
                        + rng.normal(size=(n_train + n_valid, 784)))
                data = numpy.clip((data - data.min()) /
                                  (data.max() - data.min()) * 255, 0, 255)
            train, valid = data[:n_train], data[n_train:]
            train_l, valid_l = tl_all[:n_train], tl_all[n_train:]
        self.class_lengths[:] = [0, len(valid), len(train)]
        self.original_data = numpy.concatenate(
            [valid, train]).astype(numpy.float32) / 255.0
        self.original_labels = numpy.concatenate(
            [valid_l, train_l]).tolist()


class MnistWorkflow(StandardWorkflow):
    """The classic Veles first workflow, TPU-native — an MLP ``layers``
    widths list lowered onto the StandardWorkflow graph."""

    def __init__(self, workflow, layers=(100, 10), **kwargs):
        cfg = root.mnist_tpu
        spec = [{"type": "all2all_tanh",
                 "output_sample_shape": (int(w),),
                 "weights_stddev": cfg.get("weights_stddev")}
                for w in layers[:-1]]
        spec.append({"type": "softmax",
                     "output_sample_shape": (int(layers[-1]),)})
        super(MnistWorkflow, self).__init__(
            workflow, name="MNIST",
            loader_factory=MnistLoader,
            loader_config={
                "minibatch_size": int(cfg.get("minibatch_size", 128)),
                "normalization_type": cfg.get("normalization", "none"),
            },
            layers=spec,
            solver=cfg.get("solver", "sgd"),
            learning_rate=float(cfg.get("learning_rate", 0.1)),
            gradient_moment=float(cfg.get("gradient_moment", 0.9)),
            weights_decay=float(cfg.get("weights_decay", 0.0)),
            # r5 quality recipe knobs (mirrors the cifar sample):
            # in-graph augmentation (flat minibatches reshape via
            # 'shape') and an lr schedule
            augment=cfg.get_dict("augment"),
            lr_schedule=cfg.get("lr_schedule", "constant"),
            lr_schedule_params=cfg.get_dict("lr_schedule_params") or {},
            decision_config={
                "fail_iterations": int(cfg.get("fail_iterations", 25)),
                "max_epochs": cfg.get("max_epochs"),
            },
            snapshotter_config={
                "prefix": cfg.get("snapshot_prefix", "mnist"),
                "compression": cfg.get("snapshot_compression", "gz"),
                "time_interval":
                    float(cfg.get("snapshot_time_interval", 5.0)),
            },
            **kwargs)


def run(load, main):
    layers = root.mnist_tpu.get("layers", [100, 10])
    load(MnistWorkflow, layers=layers)
    main()
