"""Transformer sequence-classification workflow — the long-context
showcase of the sequence stack (Embedding → TransformerBlock × N →
mean-pool → softmax head).

No reference analogue: sequence models never left the untested Znicz
submodule (manualrst_veles_algorithms.rst:115-140); this sample exists
because long-context is first-class in the TPU rebuild — the same
blocks scale over the ``sp`` (ring attention), ``tp`` and ``ep`` mesh
axes.

Task (synthetic, attention-hard): every sequence contains exactly one
MARKER token; the label is the token that immediately FOLLOWS the
marker (the classic induction pattern).  Position-independent lookup —
a bag-of-tokens model is at chance, an attention head solves it.

Run: ``python -m veles_tpu veles_tpu/samples/transformer.py \\
-c "root.transformer_tpu.update({'max_epochs': 20})"``
"""

import numpy

from veles_tpu.config import root
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.models.standard import StandardWorkflow

MARKER = 0  # token reserved as the lookup marker


class InductionLoader(FullBatchLoader):
    """Sequences [N, seq] over a vocab; label = token after the single
    MARKER occurrence."""

    def load_data(self):
        cfg = root.transformer_tpu
        vocab = int(cfg.get("vocab", 16))
        seq = int(cfg.get("seq", 32))
        n_train = int(cfg.get("synthetic_train", 8192))
        n_valid = int(cfg.get("synthetic_valid", 1024))
        tot = n_train + n_valid
        rng = numpy.random.default_rng(int(cfg.get("seed", 99)))
        # tokens 1..vocab-1; MARKER inserted at a random position with
        # a random payload token after it
        data = rng.integers(1, vocab, (tot, seq))
        pos = rng.integers(0, seq - 1, tot)
        payload = rng.integers(1, vocab, tot)
        data[numpy.arange(tot), pos] = MARKER
        data[numpy.arange(tot), pos + 1] = payload
        self.class_lengths[:] = [0, n_valid, n_train]
        self.original_data = data.astype(numpy.int32)
        self.original_labels = payload.tolist()


class TransformerWorkflow(StandardWorkflow):
    """Embedding → blocks → mean-pool → softmax over the vocab."""

    def __init__(self, workflow, **kwargs):
        cfg = root.transformer_tpu
        # {'dp': 2, 'sp': 4}-style axis dict -> device mesh: dp splits
        # the batch, sp sequence-shards attention through the ring
        # (parallel/mesh.py axis conventions)
        mesh = None
        raw = cfg.get_dict("mesh")
        if raw:
            from veles_tpu.parallel import build_mesh
            mesh = build_mesh(raw)
        vocab = int(cfg.get("vocab", 16))
        dim = int(cfg.get("dim", 64))
        blocks = int(cfg.get("blocks", 2))
        heads = int(cfg.get("heads", 4))
        n_experts = int(cfg.get("n_experts", 0))
        spec = [{"type": "embedding", "vocab": vocab, "dim": dim}]
        spec += [{"type": "transformer_block", "heads": heads,
                  "causal": bool(cfg.get("causal", False)),
                  "n_experts": n_experts,
                  "top_k": int(cfg.get("top_k", 2)),
                  # attention core pin: "flash" | "pallas" |
                  # "blockwise" | "dense" (None = auto; mha_apply)
                  "attn_impl": cfg.get("attn_impl"),
                  # long sequences: stream K/V in blocks instead of
                  # materializing [seq, seq] scores (ops/attention.py)
                  "attn_block_size": (
                      int(cfg.get("attn_block_size"))
                      if cfg.get("attn_block_size") else None)}
                 for _ in range(blocks)]
        spec += [{"type": "mean_pool_seq"},
                 {"type": "softmax", "output_sample_shape": (vocab,)}]
        kwargs.setdefault("mesh", mesh)  # explicit caller mesh wins
        super(TransformerWorkflow, self).__init__(
            workflow, name="Transformer",
            loader_factory=InductionLoader,
            loader_config={
                "minibatch_size": int(cfg.get("minibatch_size", 128)),
                "normalization_type": "none",
            },
            layers=spec,
            solver=cfg.get("solver", "adam"),
            learning_rate=float(cfg.get("learning_rate", 1e-3)),
            gradient_moment=float(cfg.get("gradient_moment", 0.9)),
            weights_decay=float(cfg.get("weights_decay", 0.0)),
            decision_config={
                "fail_iterations": int(cfg.get("fail_iterations", 15)),
                "max_epochs": cfg.get("max_epochs"),
            },
            snapshotter_config={
                "prefix": cfg.get("snapshot_prefix", "transformer"),
                "time_interval":
                    float(cfg.get("snapshot_time_interval", 1e9)),
            },
            **kwargs)


def run(load, main):
    load(TransformerWorkflow)
    main()
