"""Config for the AlexNet/ImageNet workflow (BASELINE config 3)."""

from veles_tpu.config import root

root.alexnet_tpu.update({
    "minibatch_size": 256,
    "classes": 1000,
    "side": 227,
    "solver": "sgd",
    "learning_rate": 0.01,
    "gradient_moment": 0.9,
    "weights_decay": 0.0005,
    "fail_iterations": 10,
    "max_epochs": 90,
    "snapshot_prefix": "alexnet",
})
