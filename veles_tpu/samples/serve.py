"""REST model serving workflow — load a trained snapshot and serve its
forward chain over HTTP (the reference paired RestfulLoader with the
RESTfulAPI unit the same way; veles/restful_api.py:78).

    python -m veles_tpu veles_tpu/samples/serve.py \
        -c "root.serve.snapshot='snapshots/mnist_current.pickle.gz'" \
        -c "root.serve.workflow='veles_tpu/samples/mnist.py'" \
        -c "root.serve.port=8080"

    curl -X POST http://localhost:8080/api \
         -d '{"input": [0.0, 0.1, ...]}'
    curl -X POST http://localhost:8080/generate \
         -d '{"prompt": [3, 1, 4], "steps": 32}'  # LM snapshots only
    curl -X POST http://localhost:8080/shutdown   # clean stop

Graph: repeater → restful_loader → [forwards from the snapshot] → api,
looping until /shutdown (or the feed closes).
"""

from veles_tpu.accelerated_units import AcceleratedWorkflow
from veles_tpu.config import root
from veles_tpu.mutable import Bool
from veles_tpu.plumbing import Repeater
from veles_tpu.restful_api import RESTfulAPI, RestfulLoader


class _ServingLoader(RestfulLoader):
    """RestfulLoader that publishes idle/closed state as gate Bools."""

    def __init__(self, workflow, **kwargs):
        super(_ServingLoader, self).__init__(workflow, **kwargs)
        #: True while the last serve produced no samples — the forward
        #: chain is gate-skipped on idle waves (no wasted device work)
        self.idle = Bool(False, "idle")
        self.stop_requested = Bool(False, "stop_requested")

    def run(self):
        super(_ServingLoader, self).run()
        self.idle.set(self.minibatch_size == 0)
        if self.closed:
            self.stop_requested.set(True)


class ServeWorkflow(AcceleratedWorkflow):
    def __init__(self, workflow, **kwargs):
        super(ServeWorkflow, self).__init__(workflow, name="Serve",
                                            **kwargs)
        cfg = root.serve
        snapshot = cfg.get("snapshot")
        if not snapshot:
            raise ValueError(
                "set root.serve.snapshot to a trained workflow snapshot")
        from veles_tpu.snapshotter import SnapshotterToFile
        # a CLI-trained snapshot pickles classes under the workflow
        # FILE's module name ('lm', 'mnist', …) — that module must be
        # importable here before unpickling (the reference resumed
        # through the same re-import, veles/__main__.py:539-589)
        wf_file = cfg.get("workflow")
        if wf_file:
            from veles_tpu.import_file import import_file_as_module
            import_file_as_module(wf_file)
        trained = SnapshotterToFile.import_file(snapshot)
        self.forwards = trained.forwards  # adopted trained chain
        sample_shape = tuple(trained.loader.minibatch_data.shape[1:])

        self.repeater = Repeater(self)
        self.repeater.link_from(self.start_point)
        self.loader = _ServingLoader(
            self, sample_shape=sample_shape,
            minibatch_size=int(cfg.get("minibatch_size", 16)),
            max_wait=float(cfg.get("max_wait", 1.0)))
        self.loader.link_from(self.repeater)

        prev = self.loader.minibatch_data
        for u in self.forwards:
            u.unlink_all()           # drop the training graph's wiring
            u.workflow = self        # re-home the adopted units
            u.input = prev
            u.gate_skip = self.loader.idle
            prev = u.output
        self.forwards[0].link_from(self.loader)
        for a, b in zip(self.forwards, self.forwards[1:]):
            b.link_from(a)

        from veles_tpu.models.transformer import TokenProjection
        self.api = RESTfulAPI(
            self, loader=self.loader,
            port=int(cfg.get("port", 0)),
            host=cfg.get("host", "127.0.0.1"),
            # continuous-batching knobs (docs/serving.md): slots,
            # queue cap and the off switch ride root.serve
            serving=bool(cfg.get("serving", True)),
            max_slots=int(cfg.get("max_slots", 4)),
            max_queue=int(cfg.get("max_queue", 32)),
            # an LM snapshot (per-token logits head) also serves
            # POST /generate — autoregressive decode off the same chain
            forwards=self.forwards
            if isinstance(self.forwards[-1], TokenProjection) else None)
        self.api.output = self.forwards[-1].output
        self.api.gate_skip = self.loader.idle
        self.api.shutdown_callback = self.request_stop
        self.api.link_from(self.forwards[-1])

        # the serving loop mirrors the training graph's termination
        # handshake: stop_requested blocks the loader and opens the end
        self.repeater.link_from(self.api)
        self.loader.gate_block = self.loader.stop_requested
        self.end_point.link_from(self.api)
        self.end_point.gate_block = ~self.loader.stop_requested

    def initialize(self, **kwargs):
        super(ServeWorkflow, self).initialize(**kwargs)
        # adopted forwards keep their trained weights (the any-PARAMS
        # refill guard skips restored params)
        self.info("serving on http://%s:%d/api (POST {\"input\": ...}; "
                  "POST /shutdown to stop)", self.api.host, self.api.port)

    def request_stop(self):
        """Thread-safe stop: close the feed; the next wave terminates
        the loop through the gates."""
        self.loader.stop_requested.set(True)
        self.loader.close()

    def run(self):
        try:
            super(ServeWorkflow, self).run()
        finally:
            self.api.stop()

    def stop(self):
        self.request_stop()
        super(ServeWorkflow, self).stop()


def run(load, main):
    load(ServeWorkflow)
    main()
