"""GTZAN genre recognition — BASELINE.json config 5.

Audio tracks under ``root.gtzan_tpu.dataset_dir`` (GTZAN layout:
``genres/<genre>/<track>.wav``) flow through the XML feature pipeline
(samples/gtzan_features.xml; schema per the reference's
veles/genre_recognition.xml) into an MLP classifier.

Run: ``python -m veles_tpu veles_tpu/samples/gtzan.py \
-c "root.gtzan_tpu.dataset_dir='/path/to/genres'"``
"""

import os

from veles_tpu.config import root
from veles_tpu.loader.sound import SoundLoader
from veles_tpu.models.standard import StandardWorkflow

FEATURES_XML = os.path.join(os.path.dirname(__file__),
                            "gtzan_features.xml")


class GtzanLoader(SoundLoader):
    def __init__(self, workflow, **kwargs):
        cfg = root.gtzan_tpu
        dataset = cfg.get("dataset_dir")
        if not dataset:
            raise ValueError(
                "set root.gtzan_tpu.dataset_dir to the GTZAN genres/ "
                "directory")
        super(GtzanLoader, self).__init__(
            workflow,
            features_xml=cfg.get("features_xml", FEATURES_XML),
            train_paths=[dataset],
            max_seconds=cfg.get("max_seconds", 30.0),
            train_ratio=float(cfg.get("train_ratio", 1.0)),
            **kwargs)

    def load_data(self):
        import numpy
        super(GtzanLoader, self).load_data()
        # GTZAN ships train data only: carve a validation span off a
        # SHUFFLED order (directory scan is genre-sorted — an unshuffled
        # front span would be entirely the alphabetically-first genres,
        # and Loader.shuffle() only permutes the train span)
        valid_frac = float(root.gtzan_tpu.get("validation_ratio", 0.2))
        n = self.class_lengths[2]
        perm = numpy.random.default_rng(42).permutation(n)
        self.original_data = self.original_data[perm]
        self.original_labels = [self.original_labels[i] for i in perm]
        n_valid = int(n * valid_frac)
        self.class_lengths[:] = [0, n_valid, n - n_valid]


class GtzanWorkflow(StandardWorkflow):
    def __init__(self, workflow, **kwargs):
        cfg = root.gtzan_tpu
        classes = int(cfg.get("classes", 10))
        super(GtzanWorkflow, self).__init__(
            workflow, name="GTZAN",
            loader_factory=GtzanLoader,
            loader_config={
                "minibatch_size": int(cfg.get("minibatch_size", 50)),
                "normalization_type": "mean_disp",
            },
            layers=[
                {"type": "all2all_tanh", "output_sample_shape": (
                    int(cfg.get("hidden", 100)),)},
                {"type": "softmax", "output_sample_shape": (classes,)},
            ],
            solver=cfg.get("solver", "adam"),
            learning_rate=float(cfg.get("learning_rate", 0.001)),
            decision_config={
                "fail_iterations": int(cfg.get("fail_iterations", 50)),
                "max_epochs": cfg.get("max_epochs"),
            },
            snapshotter_config={
                "prefix": cfg.get("snapshot_prefix", "gtzan"),
            },
            **kwargs)


def run(load, main):
    load(GtzanWorkflow)
    main()
