"""Kohonen map demo — the reference's DemoKohonen workflow
(manualrst_veles_algorithms.rst "Kohonen maps"): a SOM grid organizes
over 2-D Gaussian clusters.

Run: ``python -m veles_tpu veles_tpu/samples/kohonen.py``
"""

import numpy

from veles_tpu.accelerated_units import AcceleratedWorkflow
from veles_tpu.config import root
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.models.kohonen import (
    KohonenDecision, KohonenForward, KohonenTrainer)
from veles_tpu.plumbing import Repeater


class ClustersLoader(FullBatchLoader):
    """2-D points around ``clusters`` Gaussian centers (the DemoKohonen
    dataset shape)."""

    span_serving = False  # per-minibatch serving: the SOM trainer is
    # not a span consumer

    def load_data(self):
        cfg = root.kohonen_tpu
        rng = numpy.random.default_rng(7)
        n = int(cfg.get("samples", 2048))
        k = int(cfg.get("clusters", 4))
        centers = rng.uniform(-1.0, 1.0, size=(k, 2))
        idx = rng.integers(0, k, n)
        pts = centers[idx] + rng.normal(scale=0.08, size=(n, 2))
        self.class_lengths[:] = [0, 0, n]
        self.original_data = pts.astype(numpy.float32)


class KohonenWorkflow(AcceleratedWorkflow):
    def __init__(self, workflow, **kwargs):
        super(KohonenWorkflow, self).__init__(workflow, name="Kohonen",
                                              **kwargs)
        cfg = root.kohonen_tpu
        shape = tuple(cfg.get("shape", (8, 8)))
        self.repeater = Repeater(self)
        self.repeater.link_from(self.start_point)
        self.loader = ClustersLoader(
            self, minibatch_size=int(cfg.get("minibatch_size", 256)))
        self.loader.link_from(self.repeater)
        self.trainer = KohonenTrainer(
            self, loader=self.loader, shape=shape,
            learning_rate=float(cfg.get("learning_rate", 0.5)))
        self.trainer.link_from(self.loader)
        self.forward = KohonenForward(
            self, weights=self.trainer.weights, shape=shape)
        self.forward.input = self.loader.minibatch_data
        # BMU mapping is the inference surface — run it once per epoch,
        # not per minibatch (the trainer computes its own winners)
        self.forward.gate_skip = ~self.loader.train_ended
        self.forward.link_from(self.trainer)
        self.decision = KohonenDecision(
            self, max_epochs=int(cfg.get("max_epochs", 10)))
        self.decision.loader = self.loader
        self.decision.trainer = self.trainer
        self.decision.link_from(self.forward)
        self.repeater.link_from(self.decision)
        self.loader.gate_block = self.decision.complete
        self.end_point.link_from(self.decision)
        self.end_point.gate_block = ~self.decision.complete


def run(load, main):
    load(KohonenWorkflow)
    main()
