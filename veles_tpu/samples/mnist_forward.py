"""MNIST inference usage example (the reference shipped
MNIST/mnist_forward.py as the "how do I run a trained model" demo).

Two sources, matching the deployment surfaces:

    python veles_tpu/samples/mnist_forward.py snapshots/mnist_current.pickle.gz
    python veles_tpu/samples/mnist_forward.py model.tar.gz   # package_export

Prints per-sample predicted digits + confidence for a batch of
validation samples drawn through the workflow's own loader (snapshot
source) or random inputs (package source).
"""

import sys

import numpy


def forward_from_snapshot(path, n=8):
    import jax.numpy as jnp
    from veles_tpu.snapshotter import SnapshotterToFile
    wf = SnapshotterToFile.import_file(path)
    loader = wf.loader
    loader.load_data()  # datasets are not stored in snapshots
    x = numpy.asarray(loader.original_data[:n], numpy.float32)
    h = jnp.asarray(x)
    for u in wf.forwards:
        params = {k: jnp.asarray(a.map_read().mem)
                  for k, a in u.param_arrays().items()}
        h = u.apply(params, h)
    return numpy.asarray(h)


def forward_from_package(path, n=8):
    from veles_tpu.package_export import load_package
    pkg = load_package(path)
    rng = numpy.random.default_rng(0)
    x = rng.random((n,) + pkg.input_shape[1:], numpy.float32)
    return numpy.asarray(pkg.run(x))


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    if not argv:
        print(__doc__)
        return 2
    path = argv[0]
    n = int(argv[1]) if len(argv) > 1 else 8
    if path.endswith((".tar.gz", ".tgz")):
        probs = forward_from_package(path, n)
    else:
        probs = forward_from_snapshot(path, n)
    for i, row in enumerate(probs):
        digit = int(numpy.argmax(row))
        print("sample %d: digit %d (p=%.3f)" % (i, digit, row[digit]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
