"""CIFAR-10 convolutional workflow — BASELINE.json config 2
(the caffe-style conv net of manualrst_veles_algorithms.rst:51,
17.21% published validation error).

Run: ``python -m veles_tpu veles_tpu/samples/cifar.py \
veles_tpu/samples/cifar_config.py``

Net (caffe cifar10_quick shape): conv5x5x32 → maxpool3/2 → conv5x5x32 →
avgpool3/2 → conv5x5x64 → avgpool3/2 → fc64 → softmax10, NHWC
throughout (the layout XLA:TPU tiles onto the MXU without transposes).
"""

import os
import pickle

import numpy

from veles_tpu.config import root
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.models.standard import StandardWorkflow


class CifarLoader(FullBatchLoader):
    """CIFAR-10 python-pickle batches from
    ``root.common.dirs.datasets``/cifar10 (data_batch_1..5 +
    test_batch); a deterministic synthetic stand-in is generated when
    absent (zero-egress build environment)."""

    def _load_batch(self, path):
        with open(path, "rb") as f:
            d = pickle.load(f, encoding="bytes")
        data = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        return data, list(d[b"labels"])

    def load_data(self):
        base = os.path.join(root.common.dirs.get("datasets", "data"),
                            "cifar10")
        batches = [os.path.join(base, "data_batch_%d" % i)
                   for i in range(1, 6)]
        test = os.path.join(base, "test_batch")
        if all(os.path.isfile(p) for p in batches + [test]):
            parts = [self._load_batch(p) for p in batches]
            train = numpy.concatenate([p[0] for p in parts])
            train_l = sum((p[1] for p in parts), [])
            valid, valid_l = self._load_batch(test)
            self.info("loaded real CIFAR-10 (%d train / %d validation)",
                      len(train), len(valid))
        else:
            n_train = int(root.cifar_tpu.get("synthetic_train", 4096))
            n_valid = int(root.cifar_tpu.get("synthetic_valid", 512))
            kind = root.cifar_tpu.get("synthetic_kind", "blobs")
            self.warning("CIFAR-10 not found under %s — generating a "
                         "deterministic synthetic stand-in (%s)",
                         base, kind)
            tot = n_train + n_valid
            if kind == "scenes":
                # the quality surrogate: shape classes with label-free
                # color statistics (veles_tpu/datasets/scenes.py);
                # synthetic_size=96 gives the STL-shaped variant
                from veles_tpu.datasets import render_scenes
                data, labels = render_scenes(
                    tot, seed=1234,
                    size=int(root.cifar_tpu.get("synthetic_size", 32)))
                data = data * 255.0
            else:
                rng = numpy.random.default_rng(1234)
                labels = rng.integers(0, 10, tot)
                # class-dependent colour blobs so the task is learnable
                centers = rng.normal(scale=0.6, size=(10, 1, 1, 3))
                data = numpy.clip(
                    centers[labels]
                    + rng.normal(scale=0.25, size=(tot, 32, 32, 3)) + 0.5,
                    0, 1) * 255
            valid, train = data[:n_valid], data[n_valid:]
            valid_l, train_l = (labels[:n_valid].tolist(),
                                labels[n_valid:].tolist())
        self.class_lengths[:] = [0, len(valid), len(train)]
        self.original_data = numpy.concatenate(
            [valid, train]).astype(numpy.float32) / 255.0
        self.original_labels = list(valid_l) + list(train_l)


class CifarWorkflow(StandardWorkflow):
    """The caffe-style CIFAR conv net as a StandardWorkflow layers spec."""

    def __init__(self, workflow, layers=None, **kwargs):
        cfg = root.cifar_tpu
        # caffe cifar10_quick shapes; Glorot-scaled uniform init (the
        # framework default) instead of caffe's fixed tiny gaussians —
        # those need thousands of epochs to escape the dead zone.
        # Activations are caffe ReLU = max(0,x), i.e. the znicz STRICT
        # relu units ("conv_relu"/"all2all_relu" are znicz softplus)
        conv_t = cfg.get("conv_type", "conv_str")
        fc_t = cfg.get("fc_type", "all2all_str")
        layers = layers or [
            {"type": conv_t, "n_kernels": 32, "kx": 5, "ky": 5,
             "padding": 2},
            {"type": "max_pooling", "kx": 3, "ky": 3, "sliding": (2, 2)},
            {"type": conv_t, "n_kernels": 32, "kx": 5, "ky": 5,
             "padding": 2},
            {"type": "avg_pooling", "kx": 3, "ky": 3, "sliding": (2, 2)},
            {"type": conv_t, "n_kernels": 64, "kx": 5, "ky": 5,
             "padding": 2},
            {"type": "avg_pooling", "kx": 3, "ky": 3, "sliding": (2, 2)},
            {"type": fc_t, "output_sample_shape": (64,)},
            {"type": "softmax", "output_sample_shape": (10,)},
        ]
        # in-graph augmentation spec (ops/augment.py), e.g.
        # root.cifar_tpu.augment = {'kind': 'image', 'pad': 4} — the
        # trainer traces it into the fused step on train minibatches
        augment = cfg.get_dict("augment")
        lr_sched = cfg.get_dict("lr_schedule_params")
        super(CifarWorkflow, self).__init__(
            workflow, name="CIFAR-10",
            loader_factory=CifarLoader,
            loader_config={
                "minibatch_size": int(cfg.get("minibatch_size", 128)),
                # caffe's cifar10_quick subtracts the mean image; the
                # mean_disp normalizer is the znicz equivalent
                "normalization_type": cfg.get("normalization",
                                              "mean_disp"),
            },
            layers=layers,
            solver=cfg.get("solver", "adam"),
            learning_rate=float(cfg.get("learning_rate", 0.002)),
            gradient_moment=float(cfg.get("gradient_moment", 0.9)),
            weights_decay=float(cfg.get("weights_decay", 0.0005)),
            augment=augment,
            lr_schedule=cfg.get("lr_schedule", "constant"),
            lr_schedule_params=lr_sched or {},
            decision_config={
                "fail_iterations": int(cfg.get("fail_iterations", 20)),
                "max_epochs": cfg.get("max_epochs"),
            },
            snapshotter_config={
                "prefix": cfg.get("snapshot_prefix", "cifar"),
                "compression": cfg.get("snapshot_compression", "gz"),
                "time_interval":
                    float(cfg.get("snapshot_time_interval", 10.0)),
            },
            **kwargs)


def run(load, main):
    load(CifarWorkflow)
    main()
