"""Language-model workflow — next-token training on token sequences.

The true LM objective (per-token cross-entropy against the input
shifted by one, teacher forcing) through the stock stack:

    Embedding → TransformerBlock × N → TokenProjection →
    EvaluatorNextToken → fused GradientDescent

No reference analogue (sequence models never left the untested Znicz
submodule — SURVEY.md §5 "long-context first-class" is a rebuild
mandate, not a port); the transformer sample keeps the pooled
CLASSIFIER head, this one trains the per-token head.  Run:

    python -m veles_tpu veles_tpu/samples/lm.py \
        -c "root.lm_tpu.update({'blocks': 4, 'dim': 256})"

Sharding comes free via the generic mesh knob
(``root.common.mesh = {'pp': 2, 'dp': -1}`` pipelines the block
trunk; ``{'dp': -1}`` data-parallel etc.).

Zero-egress corpus: a procedural order-2 Markov token stream with a
planted low-rank transition structure — enough signal that the
bigram-optimal cross-entropy is markedly below the unigram one, so
learning curves prove the objective trains (the result file records
both anchors).  At the defaults the model lands ~0.05 nats from the
bigram optimum: val CE 3.34–3.40 vs h_bigram 3.29, h_unigram 4.09
(TPU v5e, 60 epochs, ~50 s).
"""

import numpy

from veles_tpu.config import root
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.models.standard import StandardWorkflow
from veles_tpu.result_provider import IResultProvider


def markov_corpus(n_seq, seq, vocab, seed=0, temp=1.5):
    """Order-2 Markov token stream: logits[a, b, :] from a planted
    low-rank tensor → transition matrix; returns tokens [n_seq, seq]
    plus the analytic unigram/bigram cross-entropy anchors (nats)."""
    rng = numpy.random.default_rng(seed)
    r = 8
    u = rng.standard_normal((vocab, r))
    v = rng.standard_normal((vocab, r))
    w = rng.standard_normal((r, vocab))
    logits = numpy.einsum("ar,br,rc->abc", u, v, w) / numpy.sqrt(r)
    logits *= temp / logits.std()
    p = numpy.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)                    # [V, V, V]
    toks = numpy.empty((n_seq, seq), numpy.int32)
    toks[:, 0] = rng.integers(0, vocab, n_seq)
    toks[:, 1] = rng.integers(0, vocab, n_seq)
    # vectorized rollout: one draw per (sequence, step)
    for t in range(2, seq):
        rows = p[toks[:, t - 2], toks[:, t - 1]]     # [n_seq, V]
        cdf = rows.cumsum(axis=1)
        draws = rng.random((n_seq, 1))
        toks[:, t] = (draws > cdf[:, :-1]).sum(axis=1)
    # anchors: entropy of the stationary unigram vs the conditional
    flat = toks.reshape(-1)
    uni = numpy.bincount(flat, minlength=vocab).astype(numpy.float64)
    uni /= uni.sum()
    h_uni = -(uni * numpy.log(numpy.clip(uni, 1e-12, None))).sum()
    h_cond = -(p * numpy.log(numpy.clip(p, 1e-12, None))).sum(-1)
    # weight conditional entropy by the empirical bigram distribution
    pairs = toks[:, :-1] * vocab + toks[:, 1:]
    big = numpy.bincount(pairs.reshape(-1),
                         minlength=vocab * vocab).astype(numpy.float64)
    big /= big.sum()
    h_big = (big.reshape(vocab, vocab) * h_cond).sum()
    return toks, float(h_uni), float(h_big)


class MarkovLoader(FullBatchLoader, IResultProvider):
    """Token sequences with planted Markov structure (labels unused —
    EvaluatorNextToken scores against the input itself)."""

    def get_metric_values(self):
        # the corpus' analytic anchors: a trained model's per-token
        # validation CE (validation_loss) should land between
        # h_bigram (the best any order-2 predictor can do) and
        # h_unigram (context-free)
        return {"h_unigram_nats": self.h_unigram_,
                "h_bigram_nats": self.h_bigram_}

    def load_data(self):
        cfg = root.lm_tpu
        seq = int(cfg.get("seq", 128))
        vocab = int(cfg.get("vocab", 64))
        n_train = int(cfg.get("synthetic_train", 8192))
        n_valid = int(cfg.get("synthetic_valid", 512))
        toks, h_uni, h_big = markov_corpus(
            n_train + n_valid, seq, vocab,
            seed=int(cfg.get("seed", 0)))
        self.class_lengths[:] = [0, n_valid, n_train]
        self.original_data = toks
        self.original_labels = [0] * (n_train + n_valid)
        #: analytic anchors for the result file: a trained model's
        #: per-token CE should land between h_bigram and h_unigram
        self.h_unigram_ = h_uni
        self.h_bigram_ = h_big


class LMWorkflow(StandardWorkflow):
    """Next-token LM on the planted-Markov corpus — or on a REAL text
    file via ``root.lm_tpu.text_path`` (byte-level BPE trained on the
    corpus itself; ``vocab_size``/``seq``/``stride`` configure the
    window loader — loader/text.py)."""

    def __init__(self, workflow, **kwargs):
        cfg = root.lm_tpu
        dim = int(cfg.get("dim", 128))
        blocks = int(cfg.get("blocks", 2))
        text_path = cfg.get("text_path")
        if text_path:
            import os

            from veles_tpu.loader.text import (BytePairVocab,
                                               FullBatchTextLM)
            # resolve the vocabulary HERE so the embedding/logits
            # width is the vocab's TRUE size — a stale vocab_path file
            # or an early min_freq stop must never leave the model a
            # different width than the ids the loader emits
            vp = cfg.get("vocab_path")
            if vp and os.path.exists(vp):
                bpe = BytePairVocab.load(vp)
            else:
                with open(text_path, encoding="utf-8") as f:
                    corpus = f.read()
                bpe = BytePairVocab.train(
                    corpus, int(cfg.get("vocab_size", 512)),
                    specials=("<eos>",))
                if vp:
                    bpe.save(vp)
            vocab = bpe.size
            loader_factory = FullBatchTextLM
            loader_config = {
                "path": text_path,
                "vocab": bpe,
                "seq_len": int(cfg.get("seq", 128)),
                "stride": cfg.get("stride"),
                "valid_fraction": float(cfg.get("valid_fraction", 0.1)),
            }
        else:
            vocab = int(cfg.get("vocab", 64))
            loader_factory = MarkovLoader
            loader_config = {}
        spec = [{"type": "embedding", "vocab": vocab, "dim": dim}]
        spec += [{"type": "transformer_block",
                  "heads": int(cfg.get("heads", 4)), "causal": True}
                 for _ in range(blocks)]
        spec += [{"type": "token_logits", "vocab": vocab}]
        loader_config.update({
            "minibatch_size": int(cfg.get("minibatch_size", 128)),
            "normalization_type": "none",
        })
        super(LMWorkflow, self).__init__(
            workflow, name="LM",
            loader_factory=loader_factory,
            loader_config=loader_config,
            layers=spec,
            loss="next_token",
            solver=cfg.get("solver", "adam"),
            learning_rate=float(cfg.get("learning_rate", 1e-3)),
            lr_schedule=cfg.get("lr_schedule", "cosine"),
            lr_schedule_params=cfg.get_dict("lr_schedule_params") or {
                "total_steps": 3800, "floor": 0.05, "warmup": 150},
            decision_config={
                "fail_iterations": int(cfg.get("fail_iterations", 60)),
                "max_epochs": cfg.get("max_epochs"),
            },
            snapshotter_config={
                "prefix": cfg.get("snapshot_prefix", "lm"),
                "time_interval":
                    float(cfg.get("snapshot_time_interval", 60.0)),
            },
            **kwargs)


def run(load, main):
    load(LMWorkflow)
    main()
