"""Config for the CIFAR-10 conv workflow (BASELINE config 2)."""

from veles_tpu.config import root

root.cifar_tpu.update({
    "minibatch_size": 128,
    "solver": "adam",
    "learning_rate": 0.002,
    "gradient_moment": 0.9,
    "weights_decay": 0.0005,
    "fail_iterations": 20,
    "max_epochs": 50,
    "snapshot_prefix": "cifar",
})
