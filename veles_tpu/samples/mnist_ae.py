"""MNIST autoencoder — the reference's MnistAE workflow family
(manualrst_veles_algorithms.rst "Autoencoder Neural Networks";
published result: 0.5478 validation RMSE).

Default topology is the FC autoencoder (784 → tanh(100) → 784, MSE on
the input); ``root.mnist_ae_tpu.conv = True`` switches to the
convolutional autoencoder shape (conv/pool encoder → deconv/depool
decoder — the ImagenetAE family, extras item 1).

Run: ``python -m veles_tpu veles_tpu/samples/mnist_ae.py``
"""

import numpy

from veles_tpu.config import root
from veles_tpu.loader.fullbatch import FullBatchLoaderMSE
from veles_tpu.models.standard import StandardWorkflow
from veles_tpu.samples.mnist import MnistLoader


class MnistAELoader(FullBatchLoaderMSE, MnistLoader):
    """MNIST images as both input and regression target
    (ref: MnistAE loader shape)."""

    def load_data(self):
        MnistLoader.load_data(self)
        if root.mnist_ae_tpu.get("conv"):
            self.original_data = self.original_data.reshape(
                -1, 28, 28, 1)
        self.original_targets = self.original_data
        self.original_labels = None  # regression: no classes

    def _maybe_upload(self):
        from veles_tpu.loader.fullbatch import FullBatchLoader
        # the AE target IS the (normalized) input: share the dataset
        # buffer instead of uploading a second copy — skipping the MSE
        # variant's separate target device_put halves the upload and
        # the HBM footprint.  With the reference's "linear" [-1, 1]
        # normalization this also makes our RMSE directly comparable
        # to its published 0.5478 (targets track normalization).
        self.original_targets = self.original_data
        FullBatchLoader._maybe_upload(self)
        if self._dataset_dev_ is not None:
            self._targets_dev_ = self._dataset_dev_


class MnistAEWorkflow(StandardWorkflow):
    def __init__(self, workflow, **kwargs):
        cfg = root.mnist_ae_tpu
        if cfg.get("conv"):
            # conv/pool encoder → deconv/depool decoder (ImagenetAE
            # family; extras item 1: Deconvolution, Depooling)
            layers = [
                {"type": "conv_relu", "n_kernels": 16, "kx": 3, "ky": 3,
                 "padding": "same"},
                {"type": "max_pooling", "kx": 2, "ky": 2},
                {"type": "depooling", "kx": 2, "ky": 2},
                {"type": "deconv", "n_kernels": 1, "kx": 3, "ky": 3,
                 "padding": "same", "activation": "sigmoid"},
            ]
        else:
            hidden = int(cfg.get("hidden", 100))
            # the reference's MNIST pipeline normalized per-sample to
            # [-1, 1] ("linear", ref normalization.py:354) — with
            # 'normalization': 'linear' the decoder output must span
            # negatives, so the head switches sigmoid → tanh and the
            # RMSE scale matches the published 0.5478
            norm = cfg.get("normalization", "none")
            out_type = "all2all_tanh" if norm == "linear" \
                else "all2all_sigmoid"
            layers = [
                {"type": "all2all_tanh", "output_sample_shape": (hidden,)},
                {"type": out_type, "output_sample_shape": (784,)},
            ]
        super(MnistAEWorkflow, self).__init__(
            workflow, name="MnistAE",
            loader_factory=MnistAELoader,
            loader_config={
                "minibatch_size": int(cfg.get("minibatch_size", 128)),
                "normalization_type": cfg.get("normalization", "none"),
            },
            layers=layers,
            loss="mse",
            solver=cfg.get("solver", "adam"),
            learning_rate=float(cfg.get("learning_rate", 0.001)),
            decision_config={
                "fail_iterations": int(cfg.get("fail_iterations", 20)),
                "max_epochs": cfg.get("max_epochs"),
            },
            snapshotter_config={
                "prefix": cfg.get("snapshot_prefix", "mnist_ae"),
            },
            **kwargs)

    def rmse(self):
        """Validation RMSE (the reference's published AE metric)."""
        loss = self.decision.epoch_metrics.get("validation_loss")
        return float(numpy.sqrt(loss)) if loss is not None else None


def run(load, main):
    load(MnistAEWorkflow)
    main()
