"""AlexNet / ImageNet workflow — BASELINE.json config 3, the driver's
target metric (samples/sec/chip).

Surface per manualrst_veles_algorithms.rst:150-164 item 6: grouped
convolution, LRN, dropout — the original 2-GPU AlexNet topology.  Run:

    python -m veles_tpu veles_tpu/samples/alexnet.py \
        veles_tpu/samples/alexnet_config.py

Real ImageNet is consumed through the directory image loader
(``root.alexnet_tpu.train_dir`` etc.); without it a synthetic
ImageNet-shaped dataset is generated (zero-egress build environment).
All convs are NHWC on the MXU; the grouped convs use XLA's native
``feature_group_count`` instead of the reference's per-group kernel
launches.
"""

import numpy

from veles_tpu.config import root
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.models.standard import StandardWorkflow


def alexnet_layers(classes=1000, dropout=0.5, space_to_depth=0,
                   side=227):
    """The canonical AlexNet layer spec (Krizhevsky et al. 2012).

    ``space_to_depth=4`` runs the 11×11/4 stem in blocked form — the
    loader pre-blocks AND stores the dataset FLAT [N, hb·wb·48]
    (4D-blocked layouts gather pathologically, ROUND5_NOTES.md §1c);
    the stem reshapes in-graph.  Numerically identical to the strided
    stem (exact parity tests); measured net effect on the full step
    in §1c."""
    s2d_hw = None
    if space_to_depth:
        s2d_hw = (-(-side // space_to_depth),) * 2
    return [
        {"type": "conv_relu", "n_kernels": 96, "kx": 11, "ky": 11,
         "sliding": (4, 4), "padding": "valid",
         "space_to_depth": space_to_depth,
         "space_to_depth_hw": s2d_hw},
        {"type": "norm", "n": 5, "alpha": 1e-4, "beta": 0.75, "k": 2.0},
        {"type": "max_pooling", "kx": 3, "ky": 3, "sliding": (2, 2)},
        {"type": "conv_relu", "n_kernels": 256, "kx": 5, "ky": 5,
         "padding": 2, "n_groups": 2},
        {"type": "norm", "n": 5, "alpha": 1e-4, "beta": 0.75, "k": 2.0},
        {"type": "max_pooling", "kx": 3, "ky": 3, "sliding": (2, 2)},
        {"type": "conv_relu", "n_kernels": 384, "kx": 3, "ky": 3,
         "padding": 1},
        {"type": "conv_relu", "n_kernels": 384, "kx": 3, "ky": 3,
         "padding": 1, "n_groups": 2},
        {"type": "conv_relu", "n_kernels": 256, "kx": 3, "ky": 3,
         "padding": 1, "n_groups": 2},
        {"type": "max_pooling", "kx": 3, "ky": 3, "sliding": (2, 2)},
        {"type": "all2all_relu", "output_sample_shape": (4096,)},
        {"type": "dropout", "dropout_ratio": dropout},
        {"type": "all2all_relu", "output_sample_shape": (4096,)},
        {"type": "dropout", "dropout_ratio": dropout},
        {"type": "softmax", "output_sample_shape": (classes,)},
    ]


def vgg_a_layers(classes=1000, dropout=0.5):
    """VGG-A (extras item 6 "Last Models: AlexNet, VGG" — the
    imagenet_workflow_vgga_config surface)."""
    def conv(k):
        return {"type": "conv_relu", "n_kernels": k, "kx": 3, "ky": 3,
                "padding": 1}

    pool = {"type": "max_pooling", "kx": 2, "ky": 2}
    return [
        conv(64), pool,
        conv(128), pool,
        conv(256), conv(256), pool,
        conv(512), conv(512), pool,
        conv(512), conv(512), pool,
        {"type": "all2all_relu", "output_sample_shape": (4096,)},
        {"type": "dropout", "dropout_ratio": dropout},
        {"type": "all2all_relu", "output_sample_shape": (4096,)},
        {"type": "dropout", "dropout_ratio": dropout},
        {"type": "softmax", "output_sample_shape": (classes,)},
    ]


class ImagenetLoader(FullBatchLoader):
    """ImageNet-shaped loader: synthetic [N, 227, 227, 3] samples unless
    ``root.alexnet_tpu.train_dir`` points at a real image tree (then the
    directory image loader should be used instead — see
    veles_tpu.loader.image.FullBatchFileImageLoader).

    The synthetic dataset is drawn **on the device** (``jax.random``):
    host-side synthesis would push gigabytes through the host↔HBM link
    for data whose only purpose is to live in HBM (and the driver's TPU
    tunnel makes that link expensive)."""

    def __init__(self, workflow, space_to_depth=None, **kwargs):
        super(ImagenetLoader, self).__init__(workflow, **kwargs)
        #: None = read root.alexnet_tpu (standalone use); the
        #: workflow passes the resolved value explicitly so loader
        #: and model cannot desync
        self.space_to_depth = space_to_depth

    def load_data(self):
        import jax
        import jax.numpy as jnp
        cfg = root.alexnet_tpu
        side = int(cfg.get("side", 227))
        classes = int(cfg.get("classes", 1000))
        n_train = int(cfg.get("synthetic_train", 2048))
        n_valid = int(cfg.get("synthetic_valid", 256))
        rng = numpy.random.default_rng(42)
        tot = n_train + n_valid
        labels = rng.integers(0, classes, tot)
        self.class_lengths[:] = [0, n_valid, n_train]
        self.original_labels = labels.tolist()
        dev = self.device.jax_device if self.device is not None else None

        s2d = int(cfg.get("space_to_depth", 0)) \
            if self.space_to_depth is None else int(self.space_to_depth)
        if s2d:
            from veles_tpu.models.conv import validate_space_to_depth
            validate_space_to_depth(side, side, 11, 11, s2d)

        @jax.jit
        def synth(key, lab):
            # stored bf16: images live in HBM only to be gathered into
            # bf16 minibatches — f32 storage doubles the gather traffic
            # and costs a whole-dataset cast every span (profiled)
            data = jax.random.uniform(key, (tot, side, side, 3),
                                      jnp.float32)
            data = data + (lab.astype(jnp.float32) / classes)[
                :, None, None, None]
            data = data.astype(jnp.bfloat16)
            if s2d:
                # pre-blocked for the space_to_depth stem (one-time,
                # at load) and stored FLAT: the per-step gather runs
                # at full rate on a 2D layout, and the stem's
                # in-graph reshape costs ~1 ms vs the ~3.5 ms the 4D
                # blocked layout lost in the span path
                from veles_tpu.models.conv import space_to_depth
                data = space_to_depth(data, s2d)
                data = data.reshape(data.shape[0], -1)
            return data

        from veles_tpu.telemetry import track_jit
        synth = track_jit("alexnet.synth_dataset", synth)
        with jax.default_device(dev):
            self.original_data = synth(
                jax.random.key(42), jnp.asarray(labels))


class AlexNetWorkflow(StandardWorkflow):
    """BASELINE config 3."""

    def __init__(self, workflow, **kwargs):
        cfg = root.alexnet_tpu
        # model = "alexnet" | "vgg_a" (the reference shipped both as
        # configs of one imagenet workflow)
        if cfg.get("model") == "vgg_a":
            s2d = 0                        # 3×3/1 stem — nothing to block
            layers = vgg_a_layers(
                classes=int(cfg.get("classes", 1000)),
                dropout=float(cfg.get("dropout", 0.5)))
        else:
            s2d = int(cfg.get("space_to_depth", 0))
            layers = alexnet_layers(
                classes=int(cfg.get("classes", 1000)),
                dropout=float(cfg.get("dropout", 0.5)),
                space_to_depth=s2d,
                side=int(cfg.get("side", 227)))
        super(AlexNetWorkflow, self).__init__(
            workflow, name="AlexNet",
            loader_factory=ImagenetLoader,
            loader_config={
                "minibatch_size": int(cfg.get("minibatch_size", 256)),
                "space_to_depth": s2d,
            },
            layers=layers,
            solver=cfg.get("solver", "sgd"),
            learning_rate=float(cfg.get("learning_rate", 0.01)),
            gradient_moment=float(cfg.get("gradient_moment", 0.9)),
            weights_decay=float(cfg.get("weights_decay", 0.0005)),
            decision_config={
                "fail_iterations": int(cfg.get("fail_iterations", 10)),
                "max_epochs": cfg.get("max_epochs"),
            },
            snapshotter_config={
                "prefix": cfg.get("snapshot_prefix", "alexnet"),
                "compression": cfg.get("snapshot_compression", "gz"),
                "time_interval":
                    float(cfg.get("snapshot_time_interval", 60.0)),
            },
            **kwargs)


def run(load, main):
    load(AlexNetWorkflow)
    main()
