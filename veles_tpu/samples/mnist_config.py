"""Config for the MNIST workflow (per-run config files are executable
Python mutating ``root`` — ref: veles/__main__.py:436-438)."""

root.mnist_tpu.update({
    "layers": [100, 10],
    "minibatch_size": 128,
    "learning_rate": 0.02,
    "gradient_moment": 0.9,
    "solver": "sgd",
    "weights_decay": 0.0,
    "fail_iterations": 25,
    "max_epochs": 5,
    "snapshot_prefix": "mnist",
    "snapshot_compression": "gz",
    "snapshot_time_interval": 5.0,
})
