"""Sample workflows (the reference shipped these via the Forge hub:
MnistSimple, CIFAR10, AlexNet — manualrst_veles_algorithms.rst)."""
