"""Result contribution contract (ref: veles/result_provider.py:1-58).

Units implementing :class:`IResultProvider` contribute to the JSON written
by ``--result-file`` (consumed by the genetics optimizer and ensemble
manager — ref: veles/workflow.py:827-849).
"""


class IResultProvider:
    """Mixin marker: implement :meth:`get_metric_values`."""

    def get_metric_values(self):
        """Return a dict of metric name -> picklable value."""
        raise NotImplementedError()
