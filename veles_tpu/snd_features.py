"""Sound feature extraction — the GTZAN pipeline (BASELINE config 5).

Rebuild of the SoundFeatureExtraction capability the reference consumed
through ctypes (veles/loader/libsndfile.py:91, snd_features.py) with its
XML feature-tree config (veles/genre_recognition.xml:1-30): a
``<features>`` document describes a tree of ``<transform>`` nodes whose
``<feature name=.../>`` leaves name the outputs.  The DSP here is
numpy/scipy (host-side — feature extraction is IO-bound preprocessing;
the TPU sees only the final feature matrix).

Transform registry (the subset the GTZAN config uses): Mix, Window,
RDFT, ComplexMagnitude, Energy, ZeroCrossings, Centroid, Rolloff, Flux,
Peaks, Merge, Stats, Fork, FrequencyBands, Rectify, Diff, Beat,
PeakAnalysis, PeakDynamicProgramming.  The beat chain is a simplified
autocorrelation tempo estimator (the reference's exact DP beat tracker
lives in the absent SoundFeatureExtraction C++ submodule).
"""

import xml.etree.ElementTree as ET

import numpy


class TransformNode:
    """One ``<transform>`` (or the root ``<features>``) element."""

    def __init__(self, name, params=None, condition=None):
        self.name = name
        self.params = params or {}
        self.condition = condition
        self.children = []
        self.features = []  # leaf output names

    def __repr__(self):
        return "<%s %r>" % (self.name, self.params)


def _parse_params(text):
    out = {}
    if not text:
        return out
    for part in text.split(","):
        key, _, value = part.partition("=")
        out[key.strip()] = value.strip()
    return out


def parse_features_xml(source):
    """Parse a feature-tree XML (path or string) → root TransformNode
    (schema per veles/genre_recognition.xml)."""
    if "<" in source:
        root = ET.fromstring(source)
    else:
        root = ET.parse(source).getroot()

    def walk(elem):
        node = TransformNode(
            elem.get("name", elem.tag),
            _parse_params(elem.get("parameters")),
            elem.get("condition"))
        for child in elem:
            if child.tag == "feature":
                node.features.append(child.get("name"))
            else:
                node.children.append(walk(child))
        return node

    top = TransformNode("features")
    for child in root:
        if child.tag == "feature":
            top.features.append(child.get("name"))
        else:
            top.children.append(walk(child))
    return top


# -- signal helpers -----------------------------------------------------------

_WINDOWS = {
    "hanning": numpy.hanning,
    "hamming": numpy.hamming,
    "blackman": numpy.blackman,
    "rectangular": numpy.ones,
}


def _frame(x, length, step):
    n = max(0, (len(x) - length) // step + 1)
    if n == 0:
        pad = numpy.zeros(length, x.dtype)
        pad[:len(x)] = x
        return pad[None, :]
    idx = numpy.arange(length)[None, :] + step * numpy.arange(n)[:, None]
    return x[idx]


class FeatureExtractor:
    """Executes a transform tree over one mono/stereo signal."""

    def __init__(self, tree, sample_rate=22050):
        self.tree = tree
        self.sample_rate = sample_rate

    def extract(self, signal):
        """signal: [n] mono or [n, channels] → {feature name: 1-D
        numpy array}."""
        out = {}
        self._run(self.tree, numpy.asarray(signal, numpy.float32), out)
        return {k: numpy.atleast_1d(numpy.asarray(v, numpy.float32)
                                    .ravel())
                for k, v in out.items()}

    # -- the walk -------------------------------------------------------------

    def _run(self, node, data, out):
        for name in node.features:
            out[name] = data
        for child in node.children:
            if child.condition and not self._condition(child.condition,
                                                       data):
                result = data  # condition false → identity (ref: Mix)
            else:
                result = self._apply(child, data)
            self._run(child, result, out)

    @staticmethod
    def _condition(cond, data):
        channels = data.shape[1] if data.ndim == 2 else 1
        return bool(eval(cond, {"__builtins__": {}},
                         {"channels": channels}))

    def _apply(self, node, data):
        fn = getattr(self, "_t_" + node.name.lower(), None)
        if fn is None:
            raise KeyError("unknown transform %r" % node.name)
        return fn(data, **node.params)

    # -- transforms -----------------------------------------------------------

    def _t_mix(self, data):
        return data.mean(axis=1) if data.ndim == 2 else data

    def _t_window(self, data, type="hanning", length="512", step=None,
                  interleaved=None):
        length = int(length)
        step = int(step) if step else length // 2
        if data.ndim > 1:  # band-split signals window per band
            return numpy.stack([
                self._t_window(band, type, str(length), str(step))
                for band in data])
        frames = _frame(data, length, step)
        return frames * _WINDOWS[type](length)[None, :]

    def _t_rdft(self, frames):
        return numpy.fft.rfft(frames, axis=-1)

    def _t_complexmagnitude(self, spec):
        return numpy.abs(spec)

    def _t_energy(self, frames):
        return numpy.sum(frames * frames, axis=-1)

    def _t_zerocrossings(self, frames):
        signs = numpy.signbit(frames)
        return numpy.sum(signs[..., 1:] != signs[..., :-1],
                         axis=-1).astype(numpy.float32)

    def _t_centroid(self, mag):
        freqs = numpy.arange(mag.shape[-1], dtype=numpy.float32)
        denom = numpy.maximum(mag.sum(axis=-1), 1e-12)
        return (mag * freqs).sum(axis=-1) / denom

    def _t_rolloff(self, mag, ratio="0.85"):
        ratio = float(ratio)
        cum = numpy.cumsum(mag, axis=-1)
        total = numpy.maximum(cum[..., -1:], 1e-12)
        return numpy.argmax(cum >= ratio * total,
                            axis=-1).astype(numpy.float32)

    def _t_flux(self, mag):
        diff = numpy.diff(mag, axis=0)
        flux = numpy.sqrt(numpy.sum(diff * diff, axis=-1))
        return numpy.concatenate([[0.0], flux])

    def _t_peaks(self, mag, number="10"):
        k = int(number)
        idx = numpy.argsort(mag, axis=-1)[..., -k:]
        vals = numpy.take_along_axis(mag, idx, axis=-1)
        return numpy.concatenate(
            [idx.astype(numpy.float32), vals], axis=-1)

    def _t_merge(self, frames):
        return numpy.asarray(frames).ravel()

    def _t_stats(self, series, interval="100", types=None):
        """Per-interval mean/stddev/skew/kurtosis (the reference Stats
        node's moment set)."""
        series = numpy.asarray(series, numpy.float64).ravel()
        interval = int(interval)
        chunks = [series[i:i + interval]
                  for i in range(0, max(len(series), 1), interval)]
        rows = []
        for c in chunks:
            if len(c) == 0:
                continue
            mean = c.mean()
            std = c.std()
            sd = std if std > 1e-12 else 1.0
            z = (c - mean) / sd
            rows.append([mean, std, (z ** 3).mean(), (z ** 4).mean()])
        return numpy.asarray(rows, numpy.float32).ravel()

    def _t_fork(self, data, factor="1"):
        return data  # children each get the same signal (ref Fork)

    def _t_frequencybands(self, data, bands="200 400 800 1600 3200",
                          filter="chebyshevII", lengths=None):
        """Chebyshev-II band-split → [n_bands+1, n] (ref
        FrequencyBands)."""
        from scipy import signal as sps
        edges = [float(b) for b in bands.split()]
        nyq = self.sample_rate / 2.0
        out = []
        lo = 0.0
        for hi in edges + [nyq * 0.99]:
            wl = max(lo / nyq, 1e-4)
            wh = min(hi / nyq, 0.99)
            if wl >= wh:
                continue
            if wl <= 1e-4:
                sos = sps.cheby2(4, 30, wh, "lowpass", output="sos")
            else:
                sos = sps.cheby2(4, 30, [wl, wh], "bandpass",
                                 output="sos")
            out.append(sps.sosfilt(sos, data))
            lo = hi
        return numpy.stack(out)

    def _t_rectify(self, data):
        return numpy.abs(data)

    def _t_diff(self, data, rectify="false", swt=None):
        d = numpy.diff(data, axis=-1)
        if str(rectify).lower() == "true":
            d = numpy.maximum(d, 0)
        return d

    def _t_beat(self, data, bands=None):
        """Onset-strength autocorrelation over summed bands →
        [lags, strength] rows (simplified tempo analysis)."""
        onset = data.sum(axis=tuple(range(data.ndim - 1))) \
            if data.ndim > 1 else data
        onset = onset - onset.mean()
        n = len(onset)
        if n < 4:
            return numpy.zeros((2, 2), numpy.float32)
        # FFT autocorrelation: the direct numpy.correlate is O(n^2)
        # and took 12s of a 15s GTZAN-track extraction; Wiener-
        # Khinchin via rfft is O(n log n) (the reference's C++
        # extractor used FFT convolution here too)
        m = 1 << int(2 * n - 1).bit_length()
        spec = numpy.fft.rfft(onset, m)
        ac = numpy.fft.irfft(spec * numpy.conj(spec), m)[:n]
        ac = ac / max(ac[0], 1e-12)
        return numpy.stack([numpy.arange(len(ac), dtype=numpy.float32),
                            ac.astype(numpy.float32)])

    def _t_peakanalysis(self, ac):
        """Top autocorrelation peaks (lag, strength) pairs."""
        lags, vals = ac[0], ac[1]
        if len(vals) < 3:
            return numpy.zeros(8, numpy.float32)
        interior = (vals[1:-1] > vals[:-2]) & (vals[1:-1] > vals[2:])
        peaks = numpy.where(interior)[0] + 1
        order = peaks[numpy.argsort(vals[peaks])[::-1]][:4]
        out = numpy.zeros(8, numpy.float32)
        for i, p in enumerate(order):
            out[2 * i] = lags[p]
            out[2 * i + 1] = vals[p]
        return out

    def _t_peakdynamicprogramming(self, ac, mind_values=None):
        """Dominant tempo lag (strongest interior peak)."""
        lags, vals = ac[0], ac[1]
        if len(vals) < 3:
            return numpy.zeros(1, numpy.float32)
        interior = (vals[1:-1] > vals[:-2]) & (vals[1:-1] > vals[2:])
        peaks = numpy.where(interior)[0] + 1
        if not len(peaks):
            return numpy.zeros(1, numpy.float32)
        best = peaks[numpy.argmax(vals[peaks])]
        return numpy.asarray([lags[best]], numpy.float32)


def extract_features(tree, signal, sample_rate=22050, flatten=True):
    """One-call API: XML tree (or its source) + signal → feature dict or
    the concatenated flat vector (sorted by feature name — the loader's
    stable MLP input layout)."""
    if isinstance(tree, str):
        tree = parse_features_xml(tree)
    feats = FeatureExtractor(tree, sample_rate).extract(signal)
    if not flatten:
        return feats
    return numpy.concatenate([feats[k] for k in sorted(feats)])
