"""ensemble — train/test fleets of model instances (L9).

Rebuild of veles/ensemble/: train mode launches N CLI subprocesses of
the same workflow with distinct seeds (and optionally sub-sampled train
sets via ``train_ratio``), aggregating each instance's ``--result-file``
metrics + snapshot path into one JSON (ref:
ensemble/base_workflow.py:59-152, model_workflow.py:137); test mode
re-runs each saved snapshot and aggregates its metrics (ref:
ensemble/test_workflow.py:102).
"""

import json
import logging
import os
import sys

from veles_tpu.cli_exec import run_cli_collect_results as _run_cli

log = logging.getLogger("ensemble")


class EnsembleTrainer:
    """Train ``size`` instances; aggregate metrics + snapshot refs
    (ref: EnsembleModelManagerBase, ensemble/base_workflow.py:59)."""

    def __init__(self, workflow_file, config_file=None, size=4,
                 train_ratio=1.0, base_overrides=(), extra_argv=(),
                 timeout=None):
        self.workflow_file = workflow_file
        self.config_file = config_file
        self.size = size
        self.train_ratio = train_ratio
        self.base_overrides = list(base_overrides)
        self.extra_argv = list(extra_argv)
        self.timeout = timeout

    def _argv(self, seed, index):
        argv = [sys.executable, "-m", "veles_tpu", self.workflow_file]
        if self.config_file:
            argv.append(self.config_file)
        for ov in self.base_overrides:
            argv += ["-c", ov]
        # distinct snapshot filenames per instance (the reference
        # suffixed snapshots per ensemble member the same way)
        argv += ["-c", "root.common.snapshot_suffix = 'ens%d'" % index]
        if self.train_ratio < 1.0:
            argv += ["-c", "root.common.ensemble_train_ratio = %r"
                     % self.train_ratio]
        argv += ["--seed", str(seed)] + self.extra_argv
        return argv

    def run(self, output_path=None):
        instances = []
        for i in range(self.size):
            log.info("training ensemble instance %d/%d", i + 1, self.size)
            results = _run_cli(self._argv(seed=4242 + i, index=i),
                               timeout=self.timeout)
            instances.append({
                "index": i,
                "seed": 4242 + i,
                "train_ratio": self.train_ratio,
                "results": results,
                "snapshot": (results or {}).get("Snapshot"),
            })
        summary = {"size": self.size, "instances": instances,
                   "workflow_file": self.workflow_file,
                   "config_file": self.config_file,
                   "base_overrides": self.base_overrides}
        summary["succeeded"] = sum(
            1 for inst in instances if inst["results"] is not None)
        if output_path:
            with open(output_path, "w") as f:
                json.dump(summary, f, indent=2, default=str)
            log.info("ensemble summary -> %s", output_path)
        return summary


class EnsembleTester:
    """Re-run every saved instance snapshot and aggregate its metrics
    (ref: EnsembleTestWorkflow, ensemble/test_workflow.py:102)."""

    def __init__(self, summary_path, extra_argv=(), timeout=None):
        self.summary_path = summary_path
        self.extra_argv = list(extra_argv)
        self.timeout = timeout

    def run(self, output_path=None):
        with open(self.summary_path) as f:
            summary = json.load(f)
        tests = []
        for inst in summary.get("instances", []):
            snap = inst.get("snapshot")
            if not snap or not os.path.isfile(snap):
                tests.append({"index": inst.get("index"),
                              "error": "snapshot missing"})
                continue
            argv = [sys.executable, "-m", "veles_tpu",
                    summary["workflow_file"]]
            if summary.get("config_file"):
                argv.append(summary["config_file"])
            for ov in summary.get("base_overrides", []):
                argv += ["-c", ov]
            argv += ["--snapshot", snap] + self.extra_argv
            results = _run_cli(argv, timeout=self.timeout)
            tests.append({"index": inst.get("index"), "results": results})
        out = {"summary": self.summary_path, "tests": tests}
        if output_path:
            with open(output_path, "w") as f:
                json.dump(out, f, indent=2, default=str)
        return out
