"""Interaction — the Shell unit (rebuild of veles/interaction.py:49):
drops into a live REPL mid-graph with the workflow in scope.  IPython
when importable, stdlib ``code.interact`` otherwise; ``gate_skip``
makes it a no-op until a debugging session flips the gate."""

from veles_tpu.units import Unit


class Shell(Unit):
    """Interactive break-point unit (ref: veles/interaction.py:49)."""

    VIEW_GROUP = "SERVICE"

    def __init__(self, workflow, banner=None, once=True, **kwargs):
        super(Shell, self).__init__(workflow, **kwargs)
        self.banner = banner or (
            "veles_tpu shell — `workflow`, `unit` are live; Ctrl-D "
            "resumes the graph")
        #: open the shell only on the first run (default) or every run
        self.once = once
        self._fired = False
        #: tests inject a callable instead of a real terminal session
        self.interact_hook = None

    def run(self):
        if self.once and self._fired:
            return
        self._fired = True
        scope = {"workflow": self._workflow, "unit": self,
                 "launcher": getattr(self._workflow, "launcher", None)}
        if self.interact_hook is not None:
            self.interact_hook(scope)
            return
        try:  # pragma: no cover - interactive only
            from IPython import embed
            embed(banner1=self.banner, user_ns=scope)
        except ImportError:  # pragma: no cover
            import code
            code.interact(banner=self.banner, local=scope)
