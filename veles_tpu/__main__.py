"""CLI entry point: ``python -m veles_tpu <workflow.py> [config.py]``.

Rebuild of veles/__main__.py:136-867.  The user workflow file implements
the ``run(load, main)`` contract (ref: __main__.py:799-818)::

    def run(load, main):
        load(MnistWorkflow, layers=[100, 10])   # construct or resume
        main()                                   # initialize + run

``load`` returns ``(workflow, restored_from_snapshot)``; ``main``
initializes the launcher-owned workflow and runs it to completion.
"""

import json
import logging
import sys

import numpy

from veles_tpu import prng
from veles_tpu.cmdline import build_parser
from veles_tpu.config import (
    apply_config_file, apply_override, load_site_configs, root)
from veles_tpu.import_file import import_file_as_module
from veles_tpu.launcher import Launcher
from veles_tpu.logger import setup_logging
from veles_tpu.snapshotter import SnapshotterToFile


def _enable_compilation_cache(path):
    """Point jax at a persistent on-disk compilation cache: the first
    run writes compiled executables there, every later CLI launch
    loads them back instead of recompiling (compile_tracker labels
    those loads ``cache="hit"`` in ``veles_jit_compiles_total``).
    The thresholds are dropped to zero because CLI runs re-pay even
    sub-second compiles on every launch; each knob is best-effort
    across jax versions."""
    import jax
    log = logging.getLogger("Main")
    try:
        jax.config.update("jax_compilation_cache_dir", str(path))
    except Exception as e:  # pragma: no cover - ancient jax
        log.warning("persistent compilation cache unavailable: %s", e)
        return
    for knob, value in (
            ("jax_persistent_cache_min_compile_time_secs", 0.0),
            ("jax_persistent_cache_min_entry_size_bytes", -1)):
        try:
            jax.config.update(knob, value)
        except Exception:  # knob not in this jax — keep its default
            pass
    try:
        # the cache initializes lazily at the FIRST compile and then
        # pins its directory — re-point it if something already jitted
        from jax.experimental.compilation_cache import (
            compilation_cache)
        compilation_cache.reset_cache()
    except Exception:
        pass
    log.info("persistent XLA compilation cache: %s", path)


class Main:
    """ref: veles/__main__.py:136."""

    def __init__(self, argv=None):
        self.argv = list(sys.argv[1:] if argv is None else argv)
        self.args = None
        self.launcher = None
        self.workflow = None
        self.restored = False

    # -- seeding (ref: __main__.py:483) ---------------------------------------

    def _seed_random(self):
        seed = self.args.seed
        if seed is None:
            prng.get().seed(42)
            return
        if seed.startswith("file:"):
            spec = seed[5:]
            path, _, nbytes = spec.partition(":")
            with open(path, "rb") as f:
                data = f.read(int(nbytes) if nbytes else 16)
            prng.get().seed(numpy.frombuffer(data, numpy.uint8))
        else:
            prng.get().seed(int(seed))

    # -- the load/main contract (ref: __main__.py:591-668) --------------------

    def _load(self, workflow_class, **kwargs):
        if self.args.snapshot:
            snap = self.args.snapshot
            if snap.startswith(("sqlite:", "odbc:")):
                # DB resume (ref odbc:// URIs, __main__.py:539-589);
                # optional "#table/prefix" suffix selects the store
                from veles_tpu.snapshotter import SnapshotterToDB
                dsn, _, frag = snap.partition("#")
                table, _, prefix = frag.partition("/")
                if dsn.startswith("odbc:"):
                    dsn = dsn[5:]
                self.workflow = SnapshotterToDB.import_db(
                    dsn, table=table or "veles", prefix=prefix or None)
            else:
                self.workflow = SnapshotterToFile.import_file(snap)
            self.workflow.workflow = self.launcher
            self.restored = True
            logging.getLogger("Main").info(
                "resumed %s from %s", type(self.workflow).__name__,
                self.args.snapshot)
        else:
            self.workflow = workflow_class(self.launcher, **kwargs)
        return self.workflow, self.restored

    def _apply_decision_overrides(self):
        """--decision KEY=VALUE: poke the decision unit directly —
        the ONLY way to extend a resumed run, whose decision carries
        its pickled max_epochs/patience state, not the config's."""
        if not self.args.decision:
            return
        dec = getattr(self.workflow, "decision", None)
        if dec is None:
            raise ValueError(
                "--decision: workflow %s has no decision unit"
                % type(self.workflow).__name__)
        import ast

        from veles_tpu.mutable import Bool
        for kv in self.args.decision:
            key, sep, val = kv.partition("=")
            if not sep or not hasattr(dec, key):
                raise ValueError(
                    "--decision %r: %s has no attribute %r"
                    % (kv, type(dec).__name__, key))
            try:
                parsed = ast.literal_eval(val)
            except (ValueError, SyntaxError):
                parsed = val
            current = getattr(dec, key)
            if isinstance(parsed, str) and not isinstance(current, str):
                # a typo like max_epochs=4O must fail HERE, not as a
                # TypeError an epoch into the resumed training
                raise ValueError(
                    "--decision %r: could not parse %r (current "
                    "value is %r)" % (kv, val, current))
            if isinstance(current, Bool):
                # shared gate Bools are referenced by the graph's
                # gate expressions — REPLACING one would detach them
                current.set(bool(parsed))
            else:
                try:
                    setattr(dec, key, parsed)
                except AttributeError:
                    raise ValueError(
                        "--decision %r: %s.%s is read-only"
                        % (kv, type(dec).__name__, key))
            logging.getLogger("Main").info(
                "decision.%s = %r", key, parsed)

    def _main(self, **kwargs):
        self._apply_decision_overrides()
        self.launcher.initialize(**kwargs)
        if self.args.debug_pickle:
            from veles_tpu.pickle_debug import (
                _try_pickle, explain_pickle_failure)
            log = logging.getLogger("Main")
            if _try_pickle(self.workflow) is None:
                log.info("workflow pickles cleanly")
            else:
                log.error("%s", explain_pickle_failure(self.workflow))
        self.launcher.run()
        if self.args.result_file:
            self.launcher.write_results(self.args.result_file)
        if self.args.export_package:
            self.workflow.package_export(self.args.export_package)
            logging.getLogger("Main").info(
                "package -> %s", self.args.export_package)

    # -- run ------------------------------------------------------------------

    # -- meta-optimization modes (L9; ref: __main__.py:716-734 dispatch) ------

    def _child_argv(self):
        """Flags forwarded to evaluation subprocesses."""
        argv = []
        if self.args.backend:
            argv += ["-a", self.args.backend]
        if self.args.device:
            argv += ["-d", str(self.args.device)]
        for kv in self.args.decision:
            argv += ["--decision", kv]
        for _ in range(self.args.verbose):
            argv += ["-v"]
        return argv

    def _write_json(self, data):
        if self.args.result_file:
            with open(self.args.result_file, "w") as f:
                json.dump(data, f, indent=2, default=str)

    def _run_optimize(self):
        from veles_tpu.genetics import (
            GeneticsOptimizer, SubprocessEvaluator)
        size, _, gens = self.args.optimize.partition(":")
        evaluator = SubprocessEvaluator(
            self.args.workflow, self.args.config,
            base_overrides=self.args.config_override,
            extra_argv=self._child_argv())
        opt = GeneticsOptimizer(
            root, evaluator, size=int(size),
            generations=int(gens) if gens else 4)
        outcome = opt.run()
        logging.getLogger("Main").info(
            "optimization done: best fitness %s with %s",
            outcome["best_fitness"], outcome["best_genes"])
        self._write_json(outcome)
        return 0

    def _run_ensemble_train(self):
        from veles_tpu.ensemble import EnsembleTrainer
        trainer = EnsembleTrainer(
            self.args.workflow, self.args.config,
            size=self.args.ensemble_train,
            train_ratio=self.args.train_ratio,
            base_overrides=self.args.config_override,
            extra_argv=self._child_argv())
        summary = trainer.run(output_path=self.args.result_file)
        return 0 if summary["succeeded"] == summary["size"] else 1

    def _run_ensemble_test(self):
        from veles_tpu.ensemble import EnsembleTester
        tester = EnsembleTester(self.args.ensemble_test,
                                extra_argv=self._child_argv())
        out = tester.run(output_path=self.args.result_file)
        ok = all("error" not in t and t.get("results") is not None
                 for t in out["tests"])
        return 0 if ok else 1

    def run(self):
        parser = build_parser()
        self.args = parser.parse_args(self.argv)
        level = (logging.WARNING, logging.INFO,
                 logging.DEBUG)[min(self.args.verbose + 1, 2)]
        setup_logging(level)
        if self.args.frontend:
            # browser-composed run (ref: __main__.py:258-332): wait for
            # one submission, then execute it in this process.  Must
            # dispatch BEFORE any config is applied — the composed run
            # owns the global root tree, not this invocation's args.
            from veles_tpu.frontend import Frontend
            frontend = Frontend(parser, port=self.args.frontend_port)
            argv = frontend.wait()
            frontend.stop()
            if not argv:
                return 1
            logging.getLogger("Main").info(
                "frontend composed: %s", " ".join(argv))
            return Main(argv).run()
        load_site_configs()
        if self.args.timings:
            root.common.timings = True
        if self.args.events_log:
            from veles_tpu.logger import events
            events.open(self.args.events_log)
        if self.args.config:
            apply_config_file(self.args.config)
        for snippet in self.args.config_override:
            apply_override(snippet)
        if self.args.health_policy:
            root.common.health.policy = self.args.health_policy
        if self.args.flightrec_dir:
            root.common.flightrec.dir = self.args.flightrec_dir
        if self.args.admin_token:
            root.common.api.admin_token = self.args.admin_token
        if self.args.prefetch is not None:
            root.common.loader.prefetch.enabled = self.args.prefetch > 0
            root.common.loader.prefetch.depth = self.args.prefetch
        if self.args.compilation_cache:
            root.common.trace.compilation_cache_dir = \
                self.args.compilation_cache
        cache_dir = root.common.trace.get("compilation_cache_dir")
        if cache_dir:
            _enable_compilation_cache(cache_dir)
        if self.args.dump_config:
            root.print_()
            return 0
        # crash forensics from the first real work onward: faulthandler
        # for native faults, SIGUSR1 for on-demand dumps, excepthook for
        # unhandled Python errors (telemetry/flight_recorder.py)
        if root.common.flightrec.get("enabled", True):
            from veles_tpu.telemetry.flight_recorder import recorder
            recorder.install()
        if self.args.ensemble_test:
            return self._run_ensemble_test()
        if not self.args.workflow:
            parser.print_help()
            return 1
        if self.args.optimize:
            return self._run_optimize()
        if self.args.ensemble_train:
            return self._run_ensemble_train()
        # replace any un-tuned Range() markers with their defaults so a
        # config written for --optimize also runs standalone
        # (ref: genetics/config.py:164 fix_config)
        from veles_tpu.genetics import fix_config
        fix_config(root)
        self._seed_random()
        workers = self.args.workers
        if workers and not self.args.listen:
            parser.error("-w/--workers requires -l/--listen "
                         "(the coordinator spawns the workers)")
        if self.args.export_package and (
                self.args.optimize or self.args.ensemble_train
                or self.args.ensemble_test):
            parser.error("--export-package applies to a single training "
                         "run, not the optimize/ensemble fleet modes")
        if workers and workers.isdigit():
            workers = int(workers)
        # the re-exec tail spawned workers run: same workflow/config/
        # overrides + the shared child flags (ref: launcher.py:75
        # filter_argv role); the spawner appends per-worker -d/-m
        worker_tail = [self.args.workflow]
        if self.args.config:
            worker_tail.append(self.args.config)
        for snippet in self.args.config_override:
            worker_tail += ["-c", snippet]
        worker_tail += self._child_argv()
        self.launcher = Launcher(
            backend=self.args.backend, device_index=self.args.device,
            listen=self.args.listen,
            master_address=self.args.master_address,
            graphics=self.args.graphics or None,
            status_url=self.args.web_status,
            profile_dir=self.args.profile,
            workers=workers, worker_cmd_tail=worker_tail)
        module = import_file_as_module(self.args.workflow)
        if not hasattr(module, "run"):
            print("workflow file must define run(load, main)",
                  file=sys.stderr)
            return 1
        if self.args.visualize:
            # construct only, print DOT
            module.run(self._load, lambda **kw: None)
            print(self.workflow.generate_graph())
            return 0
        module.run(self._load, self._main)
        return 0


def main(argv=None):
    return Main(argv).run()


if __name__ == "__main__":
    sys.exit(main())
