"""Pluggable data normalizers (rebuild of veles/normalization.py).

Registry-addressed by ``MAPPING`` name (ref: veles/normalization.py:110),
with the reference's analyze / normalize / denormalize + picklable
``state`` contract.  Analysis runs host-side over numpy minibatches at
initialize time; ``normalize`` is written with operations that work on
both numpy arrays (host path) and jax arrays (traced into the loader's
device gather), so the same normalizer serves both worlds.

Kinds (ref MAPPING classes, normalization.py:260-642): none, linear,
range_linear, mean_disp, external_mean, internal_mean, exp, pointwise.
"""

import numpy

from veles_tpu.unit_registry import MappedUnitRegistry


class UninitializedStateError(Exception):
    pass


class NormalizerBase(metaclass=MappedUnitRegistry):
    """analyze(data) accumulates statistics; normalize(data) -> data
    transformed; denormalize inverts it (ref: normalization.py:124)."""

    mapping_root = True
    hide_from_registry = True

    def __init__(self, state=None, **kwargs):
        self._initialized = False
        if state is not None:
            self.state = state

    # -- state ----------------------------------------------------------------

    @property
    def is_initialized(self):
        return self._initialized

    @property
    def state(self):
        """Picklable dict of accumulated statistics."""
        return {k: v for k, v in self.__dict__.items()
                if not k.endswith("_")}

    @state.setter
    def state(self, value):
        self.__dict__.update(value)

    #: constructor configuration preserved across reset() (statistics
    #: are discarded, configuration is not)
    CONFIG_ATTRS = ()

    def reset(self):
        cfg = {a: getattr(self, a) for a in self.CONFIG_ATTRS}
        fresh = type(self)()
        self.__dict__.clear()
        self.__dict__.update(fresh.__dict__)
        self.__dict__.update(cfg)
        self._post_reset()

    def _post_reset(self):
        pass

    # -- contract --------------------------------------------------------------

    def analyze(self, data):
        """Accumulate statistics over one batch (numpy)."""
        self._initialized = True

    def _assert_initialized(self):
        if not self._initialized:
            raise UninitializedStateError(
                "%s: analyze() never ran" % type(self).__name__)

    def normalize(self, data):
        raise NotImplementedError()

    def denormalize(self, data):
        raise NotImplementedError()

    def analyze_and_normalize(self, data):
        self.analyze(data)
        return self.normalize(data)


class StatelessNormalizer(NormalizerBase):
    """Needs no analysis pass (ref: normalization.py:260)."""

    hide_from_registry = True

    def __init__(self, state=None, **kwargs):
        super(StatelessNormalizer, self).__init__(state, **kwargs)
        self._initialized = True

    def analyze(self, data):
        pass


class NoneNormalizer(StatelessNormalizer):
    """Identity (ref: normalization.py "none")."""

    MAPPING = "none"

    def normalize(self, data):
        return data

    def denormalize(self, data):
        return data


class LinearNormalizer(StatelessNormalizer):
    """Scale each *sample* into [vmin, vmax] by its own extrema
    (ref: normalization.py:347 "linear")."""

    MAPPING = "linear"
    CONFIG_ATTRS = ("interval",)

    def __init__(self, state=None, interval=(-1.0, 1.0), **kwargs):
        self.interval = tuple(interval)
        super(LinearNormalizer, self).__init__(state, **kwargs)

    def normalize(self, data):
        vmin, vmax = self.interval
        flat = data.reshape(data.shape[0], -1)
        lo = flat.min(axis=1, keepdims=True)
        hi = flat.max(axis=1, keepdims=True)
        span = hi - lo
        span = span + (span == 0)
        out = (flat - lo) / span * (vmax - vmin) + vmin
        return out.reshape(data.shape).astype(data.dtype)

    def denormalize(self, data):
        raise NotImplementedError(
            "per-sample linear normalization is not invertible")


class RangeLinearNormalizer(NormalizerBase):
    """Scale by the global extrema of the training set into [vmin, vmax]
    (ref: normalization.py:398 "range_linear")."""

    MAPPING = "range_linear"
    CONFIG_ATTRS = ("interval",)

    def __init__(self, state=None, interval=(-1.0, 1.0), **kwargs):
        self.interval = tuple(interval)
        self.dmin = None
        self.dmax = None
        super(RangeLinearNormalizer, self).__init__(state, **kwargs)

    def analyze(self, data):
        dmin = float(numpy.min(data))
        dmax = float(numpy.max(data))
        self.dmin = dmin if self.dmin is None else min(self.dmin, dmin)
        self.dmax = dmax if self.dmax is None else max(self.dmax, dmax)
        self._initialized = True

    def normalize(self, data):
        self._assert_initialized()
        vmin, vmax = self.interval
        span = (self.dmax - self.dmin) or 1.0
        return ((data - self.dmin) / span * (vmax - vmin) + vmin).astype(
            data.dtype)

    def denormalize(self, data):
        self._assert_initialized()
        vmin, vmax = self.interval
        span = (self.dmax - self.dmin) or 1.0
        return ((data - vmin) / (vmax - vmin) * span + self.dmin).astype(
            data.dtype)


class MeanDispNormalizer(NormalizerBase):
    """Subtract per-feature mean, divide by per-feature peak-to-peak
    dispersion (ref: normalization.py:284 "mean_disp")."""

    MAPPING = "mean_disp"

    def __init__(self, state=None, **kwargs):
        self.sum = None
        self.count = 0
        self.dmin = None
        self.dmax = None
        super(MeanDispNormalizer, self).__init__(state, **kwargs)

    def analyze(self, data):
        arr = numpy.asarray(data, numpy.float64)
        s = arr.sum(axis=0)
        self.sum = s if self.sum is None else self.sum + s
        self.count += arr.shape[0]
        dmin = arr.min(axis=0)
        dmax = arr.max(axis=0)
        self.dmin = dmin if self.dmin is None \
            else numpy.minimum(self.dmin, dmin)
        self.dmax = dmax if self.dmax is None \
            else numpy.maximum(self.dmax, dmax)
        self._initialized = True

    @property
    def mean(self):
        self._assert_initialized()
        return (self.sum / max(self.count, 1)).astype(numpy.float32)

    @property
    def rdisp(self):
        self._assert_initialized()
        disp = (self.dmax - self.dmin)
        disp = disp + (disp == 0)
        return (1.0 / disp).astype(numpy.float32)

    def normalize(self, data):
        dt = data.dtype
        return ((data - self.mean) * self.rdisp).astype(dt)

    def denormalize(self, data):
        return (data / self.rdisp + self.mean).astype(data.dtype)


class ExternalMeanNormalizer(NormalizerBase):
    """Subtract a user-provided mean array
    (ref: normalization.py "external_mean")."""

    MAPPING = "external_mean"
    CONFIG_ATTRS = ("mean_source",)

    def _post_reset(self):
        if self.mean_source is not None:
            self._initialized = True

    def __init__(self, state=None, mean_source=None, **kwargs):
        self.mean_source = None
        if mean_source is not None:
            self.mean_source = numpy.asarray(mean_source)
        super(ExternalMeanNormalizer, self).__init__(state, **kwargs)
        if self.mean_source is not None:
            self._initialized = True

    def analyze(self, data):
        if self.mean_source is None:
            raise ValueError("external_mean requires mean_source")
        self._initialized = True

    def normalize(self, data):
        self._assert_initialized()
        return (data - self.mean_source.astype(data.dtype)).astype(data.dtype)

    def denormalize(self, data):
        self._assert_initialized()
        return (data + self.mean_source.astype(data.dtype)).astype(data.dtype)


class InternalMeanNormalizer(NormalizerBase):
    """Subtract the training-set mean (ref: "internal_mean")."""

    MAPPING = "internal_mean"

    def __init__(self, state=None, **kwargs):
        self.sum = None
        self.count = 0
        super(InternalMeanNormalizer, self).__init__(state, **kwargs)

    def analyze(self, data):
        arr = numpy.asarray(data, numpy.float64)
        s = arr.sum(axis=0)
        self.sum = s if self.sum is None else self.sum + s
        self.count += arr.shape[0]
        self._initialized = True

    @property
    def mean(self):
        self._assert_initialized()
        return (self.sum / max(self.count, 1)).astype(numpy.float32)

    def normalize(self, data):
        return (data - self.mean.astype(data.dtype)).astype(data.dtype)

    def denormalize(self, data):
        return (data + self.mean.astype(data.dtype)).astype(data.dtype)


class ExpNormalizer(StatelessNormalizer):
    """Sigmoid squash (ref: normalization.py "exp")."""

    MAPPING = "exp"

    def normalize(self, data):
        return (1.0 / (1.0 + numpy.exp(-numpy.asarray(
            data, numpy.float32)))).astype(data.dtype)

    def denormalize(self, data):
        arr = numpy.clip(numpy.asarray(data, numpy.float32), 1e-7, 1 - 1e-7)
        return numpy.log(arr / (1.0 - arr)).astype(data.dtype)


class PointwiseNormalizer(NormalizerBase):
    """Per-feature linear map into [-1, 1] computed from per-feature
    extrema (ref: normalization.py "pointwise")."""

    MAPPING = "pointwise"

    def __init__(self, state=None, **kwargs):
        self.dmin = None
        self.dmax = None
        super(PointwiseNormalizer, self).__init__(state, **kwargs)

    def analyze(self, data):
        arr = numpy.asarray(data)
        dmin = arr.min(axis=0)
        dmax = arr.max(axis=0)
        self.dmin = dmin if self.dmin is None \
            else numpy.minimum(self.dmin, dmin)
        self.dmax = dmax if self.dmax is None \
            else numpy.maximum(self.dmax, dmax)
        self._initialized = True

    def normalize(self, data):
        self._assert_initialized()
        span = self.dmax - self.dmin
        span = span + (span == 0)
        out = (data - self.dmin.astype(data.dtype)) \
            / span.astype(data.dtype) * 2.0 - 1.0
        return out.astype(data.dtype)

    def denormalize(self, data):
        self._assert_initialized()
        span = self.dmax - self.dmin
        span = span + (span == 0)
        return ((data + 1.0) / 2.0 * span.astype(data.dtype)
                + self.dmin.astype(data.dtype)).astype(data.dtype)


def get_normalizer(name, **kwargs):
    """Factory by MAPPING name (ref: NormalizerRegistry)."""
    cls = MappedUnitRegistry.get_factory("NormalizerBase", name)
    return cls(**kwargs)
