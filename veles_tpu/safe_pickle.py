"""Restricted unpickling for network frames.

The ZeroMQ surfaces (streaming ingest, avatar bridging, the plot
PUB/SUB channel) carry pickled *data* — numpy arrays, scalars and
plain containers — but ``pickle.loads`` on a network frame is an
arbitrary-code-execution primitive the moment an endpoint is widened
beyond loopback (the reference had the same exposure through txzmq's
streamed pickling, veles/txzmq/connection.py:255-340).
``safe_loads`` replaces it on every receive path: only the allowlisted
constructors below can appear in a frame, anything else raises
``pickle.UnpicklingError``.

``warn_if_public`` adds the loud log line when a socket is
bound/connected beyond localhost — the codec stops code execution, but
an open ingest port is still a data-injection surface the operator
should know about.
"""

import io
import pickle

#: module -> allowed attribute names.  Everything needed to rebuild
#: numpy arrays/scalars/dtypes plus harmless builtin containers —
#: nothing that can execute code on construction.
_ALLOWED = {
    "builtins": {
        "list", "dict", "tuple", "set", "frozenset", "bytearray",
        "complex", "slice", "range", "bool", "int", "float", "str",
        "bytes", "NoneType",
    },
    "collections": {"OrderedDict", "deque", "defaultdict", "Counter"},
    "numpy": {"ndarray", "dtype", "matrix"},
    # bf16-typed host mirrors (the bf16 trunk policy) pickle a
    # reference to the ml_dtypes scalar type — data-only constructors
    "ml_dtypes": {"bfloat16", "float8_e4m3fn", "float8_e5m2"},
    "numpy.core.multiarray": {"_reconstruct", "scalar"},
    "numpy._core.multiarray": {"_reconstruct", "scalar"},  # numpy >= 2
    "numpy.core.numeric": {"_frombuffer"},
    "numpy._core.numeric": {"_frombuffer"},
    "_codecs": {"encode"},  # numpy pickles route text through this
}


class RestrictedUnpickler(pickle.Unpickler):
    def find_class(self, module, name):
        if name in _ALLOWED.get(module, ()):
            return super(RestrictedUnpickler, self).find_class(
                module, name)
        raise pickle.UnpicklingError(
            "network frame references %s.%s — not in the data-only "
            "allowlist (veles_tpu/safe_pickle.py)" % (module, name))


def safe_loads(blob):
    """``pickle.loads`` restricted to plain data constructors."""
    return RestrictedUnpickler(io.BytesIO(blob)).load()


def warn_if_public(endpoint, logger):
    """Loud warning when a ZMQ endpoint reaches beyond loopback."""
    ep = str(endpoint)
    local = any(h in ep for h in
                ("127.0.0.1", "localhost", "ipc://", "inproc://", "::1"))
    if not local:
        logger.warning(
            "endpoint %s is reachable beyond loopback — frames are "
            "decoded with a restricted unpickler (no code execution), "
            "but anyone who can reach the socket can inject data",
            endpoint)
