"""Report rendering backends (rebuild of veles/publishing/*_backend.py
+ registry.py).  Each backend renders the Publisher's payload dict to a
file and returns its path."""

import json
import os


def _slug(name):
    return "".join(c if c.isalnum() else "_" for c in name).lower()


def _metrics_rows(metrics):
    return [(k, v) for k, v in sorted(metrics.items())]


class MarkdownBackend:
    """ref: publishing/markdown_backend.py role."""

    NAME = "markdown"
    EXT = ".md"

    def render(self, payload, out_dir):
        lines = ["# %s" % payload["title"], "",
                 "- workflow: `%s` (%s)" % (payload["workflow"],
                                            payload["workflow_class"]),
                 "- generated: %s" % payload["generated"],
                 "- checksum: `%s`" % payload["checksum"][:16], "",
                 "## Metrics", "",
                 "| metric | value |", "|---|---|"]
        for k, v in _metrics_rows(payload["metrics"]):
            lines.append("| %s | %s |" % (k, v))
        lines += ["", "## Unit timings", "",
                  "| unit | class | runs | seconds |", "|---|---|---|---|"]
        for u in payload["units"]:
            lines.append("| %s | %s | %d | %.4f |"
                         % (u["name"], u["class"], u["runs"],
                            u["seconds"]))
        if payload.get("plots"):
            lines += ["", "## Plots", ""]
            for name, plot in sorted(payload["plots"].items()):
                lines.append("- **%s** (%s)" % (name, plot.get("kind")))
        lines += ["", "## Workflow graph", "", "```dot",
                  payload["graph_dot"], "```", ""]
        path = os.path.join(out_dir,
                            _slug(payload["workflow"]) + "_report.md")
        with open(path, "w") as f:
            f.write("\n".join(lines))
        return path


class HTMLBackend:
    """Standalone HTML page; plots render as PNGs beside it when
    matplotlib is available."""

    NAME = "html"
    EXT = ".html"

    def render(self, payload, out_dir):
        imgs = []
        try:
            from veles_tpu.graphics_client import render_payload
            for name, plot in sorted(payload.get("plots", {}).items()):
                png = os.path.join(
                    out_dir, "%s_%s.png" % (_slug(payload["workflow"]),
                                            _slug(name)))
                render_payload(plot).savefig(png)
                imgs.append((name, os.path.basename(png)))
        except Exception:  # plots are garnish; the report must land
            imgs = []
        rows = "".join("<tr><td>%s</td><td>%s</td></tr>" % kv
                       for kv in _metrics_rows(payload["metrics"]))
        figures = "".join(
            '<figure><img src="%s" alt="%s"/><figcaption>%s'
            "</figcaption></figure>" % (src, name, name)
            for name, src in imgs)
        html = (
            "<!DOCTYPE html><html><head><meta charset='utf-8'>"
            "<title>%s</title></head><body><h1>%s</h1>"
            "<p>%s — generated %s</p>"
            "<h2>Metrics</h2><table>%s</table>%s</body></html>"
            % (payload["title"], payload["title"], payload["workflow"],
               payload["generated"], rows, figures))
        path = os.path.join(out_dir,
                            _slug(payload["workflow"]) + "_report.html")
        with open(path, "w") as f:
            f.write(html)
        return path


class NotebookBackend:
    """Jupyter notebook (ref: publishing/ipython_backend.py role): one
    markdown summary cell + a code cell reloading the metrics."""

    NAME = "notebook"
    EXT = ".ipynb"

    def render(self, payload, out_dir):
        md = ["# %s\n" % payload["title"],
              "%s — generated %s\n" % (payload["workflow"],
                                       payload["generated"]),
              "\n## Metrics\n"]
        md += ["- **%s**: %s\n" % kv
               for kv in _metrics_rows(payload["metrics"])]
        nb = {
            "nbformat": 4, "nbformat_minor": 5,
            "metadata": {"language_info": {"name": "python"}},
            "cells": [
                {"cell_type": "markdown", "metadata": {}, "source": md},
                {"cell_type": "code", "metadata": {},
                 "execution_count": None, "outputs": [],
                 "source": ["metrics = %r\n" % payload["metrics"],
                            "metrics\n"]},
            ],
        }
        path = os.path.join(out_dir,
                            _slug(payload["workflow"]) + "_report.ipynb")
        with open(path, "w") as f:
            json.dump(nb, f, indent=1, default=str)
        return path


class LaTeXBackend:
    """LaTeX article + PDF when a TeX engine is on PATH (ref:
    publishing/pdf_backend.py role — the reference shelled out to an
    external renderer too).  Without TeX the ``.tex`` artifact is the
    deliverable."""

    NAME = "latex"
    EXT = ".tex"

    @staticmethod
    def _esc(s):
        out = []
        for ch in str(s):
            if ch in "&%$#_{}":
                out.append("\\" + ch)
            elif ch == "\\":
                out.append(r"\textbackslash{}")
            elif ch == "~":
                out.append(r"\textasciitilde{}")
            elif ch == "^":
                out.append(r"\textasciicircum{}")
            else:
                out.append(ch)
        return "".join(out)

    def render(self, payload, out_dir):
        e = self._esc
        lines = [
            r"\documentclass{article}",
            r"\usepackage{booktabs}",
            r"\usepackage{graphicx}",
            r"\title{%s}" % e(payload["title"]),
            r"\date{%s}" % e(payload["generated"]),
            r"\begin{document}",
            r"\maketitle",
            r"\noindent workflow: \texttt{%s} (%s); checksum "
            r"\texttt{%s}" % (e(payload["workflow"]),
                              e(payload["workflow_class"]),
                              e(payload["checksum"][:16])),
            r"\section*{Metrics}",
            r"\begin{tabular}{ll}", r"\toprule",
            r"metric & value \\", r"\midrule",
        ]
        for k, v in _metrics_rows(payload["metrics"]):
            lines.append(r"%s & %s \\" % (e(k), e(v)))
        lines += [r"\bottomrule", r"\end{tabular}",
                  r"\section*{Unit timings}",
                  r"\begin{tabular}{llrr}", r"\toprule",
                  r"unit & class & runs & seconds \\", r"\midrule"]
        for u in payload["units"]:
            lines.append(r"%s & %s & %d & %.4f \\"
                         % (e(u["name"]), e(u["class"]), u["runs"],
                            u["seconds"]))
        lines += [r"\bottomrule", r"\end{tabular}"]
        if payload.get("plots"):
            lines += [r"\section*{Plots}", r"\begin{itemize}"]
            lines += [r"\item \textbf{%s} (%s)"
                      % (e(name), e(plot.get("kind")))
                      for name, plot in sorted(payload["plots"].items())]
            lines += [r"\end{itemize}"]
        lines += [r"\end{document}", ""]
        path = os.path.join(out_dir,
                            _slug(payload["workflow"]) + "_report.tex")
        with open(path, "w") as f:
            f.write("\n".join(lines))
        return self._try_pdf(path, out_dir) or path

    @staticmethod
    def _try_pdf(tex_path, out_dir):
        import shutil
        import subprocess
        for engine in ("tectonic", "pdflatex", "xelatex"):
            exe = shutil.which(engine)
            if not exe:
                continue
            args = [exe, tex_path] if engine == "tectonic" else \
                [exe, "-interaction=nonstopmode",
                 "-output-directory", out_dir, tex_path]
            try:
                subprocess.run(args, cwd=out_dir, capture_output=True,
                               timeout=120, check=True)
            except Exception:
                continue  # this engine failed; try the next one
            pdf = os.path.splitext(tex_path)[0] + ".pdf"
            if os.path.isfile(pdf):
                return pdf
        return None


class ConfluenceBackend:
    """Publish the report as a Confluence page (ref:
    publishing/confluence_backend.py + confluence.py — the reference
    logged in over XML-RPC and stored storage-format content; this
    rebuild targets the REST API: POST /rest/api/content with
    storage-format XHTML).  Configuration comes from the backend
    kwargs/config: ``server``, ``space``, ``token`` (or
    ``username``/``password``), optional ``page`` title and ``parent``
    page id.  Also writes the page XHTML beside the snapshots so the
    report survives an unreachable server."""

    NAME = "confluence"
    EXT = ".xhtml"

    def __init__(self, server=None, space=None, token=None,
                 username=None, password=None, page=None, parent=None,
                 timeout=30):
        from veles_tpu.config import root
        cfg = root.common.publishing.confluence
        self.server = server or cfg.get("server")
        self.space = space or cfg.get("space")
        self.token = token or cfg.get("token")
        self.username = username or cfg.get("username")
        self.password = password or cfg.get("password")
        self.page = page or cfg.get("page")
        self.parent = parent or cfg.get("parent")
        self.timeout = timeout
        self.url = None  # the published page URL, for callers/tests

    @staticmethod
    def _esc(s):
        return (str(s).replace("&", "&amp;").replace("<", "&lt;")
                .replace(">", "&gt;"))

    def storage_xhtml(self, payload):
        """Confluence storage-format body."""
        e = self._esc
        rows = "".join("<tr><td>%s</td><td>%s</td></tr>" % (e(k), e(v))
                       for k, v in _metrics_rows(payload["metrics"]))
        units = "".join(
            "<tr><td>%s</td><td>%s</td><td>%d</td><td>%.4f</td></tr>"
            % (e(u["name"]), e(u["class"]), u["runs"], u["seconds"])
            for u in payload["units"])
        return (
            "<p>workflow <code>%s</code> (%s) — generated %s — checksum "
            "<code>%s</code></p>"
            "<h2>Metrics</h2><table><tbody>"
            "<tr><th>metric</th><th>value</th></tr>%s</tbody></table>"
            "<h2>Unit timings</h2><table><tbody>"
            "<tr><th>unit</th><th>class</th><th>runs</th>"
            "<th>seconds</th></tr>%s</tbody></table>"
            % (e(payload["workflow"]), e(payload["workflow_class"]),
               e(payload["generated"]), e(payload["checksum"][:16]),
               rows, units))

    def render(self, payload, out_dir):
        import base64
        import json as _json
        import urllib.request
        body = self.storage_xhtml(payload)
        path = os.path.join(out_dir,
                            _slug(payload["workflow"]) + "_report.xhtml")
        with open(path, "w") as f:
            f.write(body)
        if not self.server or not self.space:
            return path  # offline render only
        doc = {
            "type": "page",
            "title": self.page or payload["title"],
            "space": {"key": self.space},
            "body": {"storage": {"value": body,
                                 "representation": "storage"}},
        }
        if self.parent:
            doc["ancestors"] = [{"id": self.parent}]
        headers = {"Content-Type": "application/json"}
        if self.token:
            headers["Authorization"] = "Bearer %s" % self.token
        elif self.username:
            cred = "%s:%s" % (self.username, self.password or "")
            headers["Authorization"] = "Basic %s" % base64.b64encode(
                cred.encode()).decode()
        req = urllib.request.Request(
            self.server.rstrip("/") + "/rest/api/content",
            data=_json.dumps(doc).encode(), headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                reply = _json.load(r)
        except Exception as e:
            # the offline .xhtml artifact above is the fallback — an
            # unreachable/refusing server must not crash the workflow's
            # end-of-train publishing step
            import logging
            logging.getLogger("ConfluenceBackend").warning(
                "publish to %s failed (%s) — offline report kept at %s",
                self.server, e, path)
            return path
        base = reply.get("_links", {}).get("base", self.server)
        webui = reply.get("_links", {}).get("webui", "")
        self.url = base + webui
        return path


BACKENDS = {b.NAME: b for b in (MarkdownBackend, HTMLBackend,
                                NotebookBackend, LaTeXBackend,
                                ConfluenceBackend)}
