"""Report rendering backends (rebuild of veles/publishing/*_backend.py
+ registry.py).  Each backend renders the Publisher's payload dict to a
file and returns its path."""

import json
import os


def _slug(name):
    return "".join(c if c.isalnum() else "_" for c in name).lower()


def _metrics_rows(metrics):
    return [(k, v) for k, v in sorted(metrics.items())]


class MarkdownBackend:
    """ref: publishing/markdown_backend.py role."""

    NAME = "markdown"
    EXT = ".md"

    def render(self, payload, out_dir):
        lines = ["# %s" % payload["title"], "",
                 "- workflow: `%s` (%s)" % (payload["workflow"],
                                            payload["workflow_class"]),
                 "- generated: %s" % payload["generated"],
                 "- checksum: `%s`" % payload["checksum"][:16], "",
                 "## Metrics", "",
                 "| metric | value |", "|---|---|"]
        for k, v in _metrics_rows(payload["metrics"]):
            lines.append("| %s | %s |" % (k, v))
        lines += ["", "## Unit timings", "",
                  "| unit | class | runs | seconds |", "|---|---|---|---|"]
        for u in payload["units"]:
            lines.append("| %s | %s | %d | %.4f |"
                         % (u["name"], u["class"], u["runs"],
                            u["seconds"]))
        if payload.get("plots"):
            lines += ["", "## Plots", ""]
            for name, plot in sorted(payload["plots"].items()):
                lines.append("- **%s** (%s)" % (name, plot.get("kind")))
        lines += ["", "## Workflow graph", "", "```dot",
                  payload["graph_dot"], "```", ""]
        path = os.path.join(out_dir,
                            _slug(payload["workflow"]) + "_report.md")
        with open(path, "w") as f:
            f.write("\n".join(lines))
        return path


class HTMLBackend:
    """Standalone HTML page; plots render as PNGs beside it when
    matplotlib is available."""

    NAME = "html"
    EXT = ".html"

    def render(self, payload, out_dir):
        imgs = []
        try:
            from veles_tpu.graphics_client import render_payload
            for name, plot in sorted(payload.get("plots", {}).items()):
                png = os.path.join(
                    out_dir, "%s_%s.png" % (_slug(payload["workflow"]),
                                            _slug(name)))
                render_payload(plot).savefig(png)
                imgs.append((name, os.path.basename(png)))
        except Exception:  # plots are garnish; the report must land
            imgs = []
        rows = "".join("<tr><td>%s</td><td>%s</td></tr>" % kv
                       for kv in _metrics_rows(payload["metrics"]))
        figures = "".join(
            '<figure><img src="%s" alt="%s"/><figcaption>%s'
            "</figcaption></figure>" % (src, name, name)
            for name, src in imgs)
        html = (
            "<!DOCTYPE html><html><head><meta charset='utf-8'>"
            "<title>%s</title></head><body><h1>%s</h1>"
            "<p>%s — generated %s</p>"
            "<h2>Metrics</h2><table>%s</table>%s</body></html>"
            % (payload["title"], payload["title"], payload["workflow"],
               payload["generated"], rows, figures))
        path = os.path.join(out_dir,
                            _slug(payload["workflow"]) + "_report.html")
        with open(path, "w") as f:
            f.write(html)
        return path


class NotebookBackend:
    """Jupyter notebook (ref: publishing/ipython_backend.py role): one
    markdown summary cell + a code cell reloading the metrics."""

    NAME = "notebook"
    EXT = ".ipynb"

    def render(self, payload, out_dir):
        md = ["# %s\n" % payload["title"],
              "%s — generated %s\n" % (payload["workflow"],
                                       payload["generated"]),
              "\n## Metrics\n"]
        md += ["- **%s**: %s\n" % kv
               for kv in _metrics_rows(payload["metrics"])]
        nb = {
            "nbformat": 4, "nbformat_minor": 5,
            "metadata": {"language_info": {"name": "python"}},
            "cells": [
                {"cell_type": "markdown", "metadata": {}, "source": md},
                {"cell_type": "code", "metadata": {},
                 "execution_count": None, "outputs": [],
                 "source": ["metrics = %r\n" % payload["metrics"],
                            "metrics\n"]},
            ],
        }
        path = os.path.join(out_dir,
                            _slug(payload["workflow"]) + "_report.ipynb")
        with open(path, "w") as f:
            json.dump(nb, f, indent=1, default=str)
        return path


BACKENDS = {b.NAME: b for b in (MarkdownBackend, HTMLBackend,
                                NotebookBackend)}
