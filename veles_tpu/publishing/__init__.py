"""publishing — end-of-train report generation (rebuild of
veles/publishing/: Publisher unit + pluggable backends).

The reference rendered to Confluence, Markdown, LaTeX/PDF and IPython
notebooks (publishing/*_backend.py); the rebuild keeps the
backend-registry shape with Markdown, HTML and notebook backends (the
Confluence uploader is out of scope in a zero-egress build — its slot
in the registry is where it would land).
"""

from veles_tpu.publishing.publisher import Publisher  # noqa: F401
from veles_tpu.publishing.backends import (  # noqa: F401
    BACKENDS, HTMLBackend, MarkdownBackend, NotebookBackend)
