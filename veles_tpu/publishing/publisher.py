"""Publisher unit (rebuild of veles/publishing/publisher.py:57):
collects everything a training-run report needs — workflow identity,
config, metrics, unit timings, plot payloads, the graph DOT — and hands
it to a rendering backend."""

import datetime
import os

from veles_tpu.config import root
from veles_tpu.units import Unit


class Publisher(Unit):
    """End-of-train report generator.  Gate it on ``decision.complete``
    (the standard wiring) so it fires once, at the end."""

    VIEW_GROUP = "SERVICE"

    def __init__(self, workflow, backend="markdown", output_dir=None,
                 title=None, backend_config=None, **kwargs):
        super(Publisher, self).__init__(workflow, **kwargs)
        self.backend_name = backend
        self.backend_config = dict(backend_config or {})
        self.output_dir = output_dir
        self.title = title
        self.destination = None

    def gather(self):
        """The report payload (ref: publisher.py collecting metrics,
        plots and the workflow graph)."""
        wf = self._workflow
        payload = {
            "title": self.title or "%s report" % wf.name,
            "generated": datetime.datetime.now().isoformat(
                timespec="seconds"),
            "workflow": wf.name,
            "workflow_class": type(wf).__name__,
            "checksum": wf.checksum(),
            "metrics": wf.gather_results(),
            "config": root.__content__(),
            "units": [
                {"name": u.name, "class": type(u).__name__,
                 "runs": u.timers.get("runs", 0),
                 "seconds": round(u.timers.get("run", 0.0), 4)}
                for u in wf.units],
            "graph_dot": wf.generate_graph(),
            "plots": {},
        }
        for u in wf.units:
            if getattr(u, "last_payload", None):
                payload["plots"][u.name] = u.last_payload
        return payload

    def run(self):
        from veles_tpu.publishing.backends import BACKENDS
        cls = BACKENDS[self.backend_name]
        backend = cls(**self.backend_config) if self.backend_config \
            else cls()
        out_dir = self.output_dir \
            or root.common.dirs.get("snapshots", ".")
        os.makedirs(out_dir, exist_ok=True)
        payload = self.gather()
        self.destination = backend.render(payload, out_dir)
        self.info("report -> %s", self.destination)
