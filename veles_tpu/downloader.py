"""Downloader unit (rebuild of veles/downloader.py:56): fetches and
unpacks a dataset archive at initialize() when the target directory is
missing.  Sources: local paths, ``file://`` and ``http(s)://`` URLs
(the build environment is zero-egress — URL fetches are expected to be
used on user machines)."""

import os
import shutil
import tarfile
import urllib.parse
import urllib.request
import zipfile

from veles_tpu.config import root
from veles_tpu.units import Unit


class Downloader(Unit):
    """Ensures ``directory`` exists, downloading+unpacking ``url`` if
    not (ref: veles/downloader.py:56 — it shelled out to wget)."""

    VIEW_GROUP = "SERVICE"

    def __init__(self, workflow, url=None, directory=None, files=(),
                 **kwargs):
        super(Downloader, self).__init__(workflow, **kwargs)
        self.url = url
        self.directory = directory
        #: files expected inside directory (presence check)
        self.files = list(files)
        self.demand("url", "directory")

    @property
    def _complete(self):
        if not os.path.isdir(self.directory):
            return False
        return all(os.path.exists(os.path.join(self.directory, f))
                   for f in self.files)

    def initialize(self, **kwargs):
        super(Downloader, self).initialize(**kwargs)
        if self._complete:
            self.debug("%s already present", self.directory)
            return
        os.makedirs(self.directory, exist_ok=True)
        archive = self._fetch()
        try:
            self._unpack(archive)
        finally:
            if archive != self.url:
                try:
                    os.unlink(archive)
                except OSError:
                    pass
        if not self._complete:
            raise RuntimeError(
                "%s: archive did not provide expected files %s"
                % (self, self.files))

    def _fetch(self):
        scheme = urllib.parse.urlparse(str(self.url)).scheme
        if scheme in ("", "file"):
            path = urllib.parse.urlparse(str(self.url)).path \
                if scheme == "file" else self.url
            if not os.path.isfile(path):
                raise FileNotFoundError(path)
            return path
        cache = root.common.dirs.get("cache", ".")
        os.makedirs(cache, exist_ok=True)
        target = os.path.join(
            cache, os.path.basename(urllib.parse.urlparse(
                self.url).path) or "download")
        self.info("downloading %s -> %s", self.url, target)
        with urllib.request.urlopen(self.url) as r, \
                open(target, "wb") as f:
            shutil.copyfileobj(r, f)
        return target

    def _unpack(self, archive):
        self.info("unpacking %s -> %s", archive, self.directory)
        if zipfile.is_zipfile(archive):
            with zipfile.ZipFile(archive) as z:
                z.extractall(self.directory)
        elif tarfile.is_tarfile(archive):
            with tarfile.open(archive) as t:
                t.extractall(self.directory, filter="data")
        else:
            shutil.copy(archive, self.directory)

    def run(self):
        pass  # all the work happens at initialize
