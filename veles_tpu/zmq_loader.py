"""ZeroMQ streaming ingestion (rebuild of veles/zmq_loader.py:74-138 —
the Mastodon bridge's job feed).

A PULL socket receives pickled samples from any producer (the
reference's JVM/Hadoop bridge; here any pyzmq PUSH peer) and serves
them as minibatches through the InteractiveLoader machinery."""

import pickle

from veles_tpu.safe_pickle import safe_loads
import threading

from veles_tpu.loader.interactive import InteractiveLoader

try:
    import zmq
    HAS_ZMQ = True
except ImportError:  # pragma: no cover
    HAS_ZMQ = False


class ZeroMQLoader(InteractiveLoader):
    """PULL-socket loader (ref: veles/zmq_loader.py:74).  Producers
    ``send_pyobj(sample)``; ``send_pyobj(None)`` closes the stream."""

    def __init__(self, workflow, endpoint=None, **kwargs):
        super(ZeroMQLoader, self).__init__(workflow, **kwargs)
        #: "tcp://host:port" to bind; None binds a random tcp port
        self.endpoint = endpoint

    def init_unpickled(self):
        super(ZeroMQLoader, self).init_unpickled()
        self._sock_ = None
        self._recv_thread_ = None

    def initialize(self, **kwargs):
        if not HAS_ZMQ:  # pragma: no cover
            raise RuntimeError("pyzmq is unavailable")
        super(ZeroMQLoader, self).initialize(**kwargs)
        if self._sock_ is not None:
            return
        ctx = zmq.Context.instance()
        self._sock_ = ctx.socket(zmq.PULL)
        if self.endpoint:
            self._sock_.bind(self.endpoint)
        else:
            port = self._sock_.bind_to_random_port("tcp://127.0.0.1")
            self.endpoint = "tcp://127.0.0.1:%d" % port
        self.info("ZeroMQ ingestion on %s", self.endpoint)
        from veles_tpu.safe_pickle import warn_if_public
        warn_if_public(self.endpoint, self)
        self._recv_thread_ = threading.Thread(
            target=self._receive_loop, daemon=True, name="zmq-ingest")
        self._recv_thread_.start()

    def _receive_loop(self):
        while True:
            try:
                blob = self._sock_.recv()
            except zmq.ZMQError:  # pragma: no cover - socket closed
                break
            try:
                sample = safe_loads(blob)
                if sample is None:
                    self.close()
                    break
                self.feed(sample)
            except Exception as e:
                # one malformed producer frame must not kill the ingest
                # thread (and with it the whole stream)
                self.warning("dropped bad ingest frame: %s", e)
