"""Dtype / precision policy.

Replaces the reference's dtype macro layer (ref: ocl/defines.cl:1-69,
veles/opencl_types.py:1-78) and the PRECISION_LEVEL Kahan/multipartial
summation knobs (ref: ocl/matrix_multiplication_precise.cl:1-46,
veles/config.py:245-248).  On TPU the equivalents are:

- a *compute dtype* for matmul/conv operands (bfloat16 feeds the MXU at
  full rate),
- an *accumulation dtype* (float32 — the MXU always accumulates in f32;
  exposing it as policy keeps the reference's "more precise summation"
  capability),
- a *parameter dtype* for master weights,
- a ``jax.lax.Precision`` level: 0 → DEFAULT, 1 → HIGH, 2 → HIGHEST,
  mirroring the reference's three GEMM precision levels.

All knobs live in ``root.common.precision`` so per-run config files tune
them exactly like the reference's ``root.common.precision_type``.
"""

import jax
import jax.numpy as jnp
import numpy

from veles_tpu.config import root

#: name -> dtype map covering everything the reference's dtype_map did
#: (veles/opencl_types.py:24-42) plus TPU-native types.
dtype_map = {
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "float32": jnp.float32,
    "float64": jnp.float64,
    "int8": jnp.int8,
    "int16": jnp.int16,
    "int32": jnp.int32,
    "int64": jnp.int64,
    "uint8": jnp.uint8,
    "uint16": jnp.uint16,
    "uint32": jnp.uint32,
    "uint64": jnp.uint64,
}

_PRECISION_LEVELS = {
    0: jax.lax.Precision.DEFAULT,
    1: jax.lax.Precision.HIGH,
    2: jax.lax.Precision.HIGHEST,
}


def compute_dtype():
    """Operand dtype for MXU ops (matmul/conv)."""
    return dtype_map[root.common.precision.get("compute_dtype", "bfloat16")]


def accum_dtype():
    """Accumulation / reduction dtype."""
    return dtype_map[root.common.precision.get("accum_dtype", "float32")]


def param_dtype():
    """Master-copy parameter dtype."""
    return dtype_map[root.common.precision.get("param_dtype", "float32")]


def matmul_precision():
    """``jax.lax.Precision`` from ``root.common.precision.level``
    (0/1/2 — the reference's PRECISION_LEVEL ladder)."""
    return _PRECISION_LEVELS[int(root.common.precision.get("level", 0))]


def as_numpy_dtype(dt):
    return numpy.dtype(dt)


def itemsize(dt):
    return numpy.dtype(dt).itemsize
