"""Snapshotter — periodic whole-workflow checkpointing.

Rebuild of veles/snapshotter.py:84-535: pickles the live workflow object
graph (parameters, solver state, loader epoch position, RNG states —
everything that isn't a volatile ``*_`` attribute) to a compressed file,
keeps a ``_current`` symlink, gates on iteration/wall-clock intervals
and on the decision's ``improved`` flag, and resumes via
:meth:`SnapshotterToFile.import_file`.

Codecs: none / gz / bz2 / xz (the reference's snappy codec is gated out
— the module isn't in this image; ref note "snappy is slow on CPython",
veles/config.py:263-265).  The ODBC backend survives as
:class:`SnapshotterToDB` behind an import guard.
"""

import bz2
import gzip
import lzma
import os
import pickle
import time

from veles_tpu.config import root
from veles_tpu.units import Unit

CODECS = {
    None: lambda p, m: open(p, m + "b"),
    "": lambda p, m: open(p, m + "b"),
    "gz": lambda p, m: gzip.open(p, m + "b"),
    "bz2": lambda p, m: bz2.open(p, m + "b"),
    "xz": lambda p, m: lzma.open(p, m + "b"),
}

EXT = {None: ".pickle", "": ".pickle", "gz": ".pickle.gz",
       "bz2": ".pickle.bz2", "xz": ".pickle.xz"}


class SnapshotterBase(Unit):
    """Common gating logic (ref: snapshotter.py:84-248).

    Fires when its gate opens AND (``decision.improved`` if linked) AND
    the interval/time_interval has elapsed.
    """

    hide_from_registry = True
    VIEW_GROUP = "SERVICE"

    def __init__(self, workflow, prefix="wf", interval=1,
                 time_interval=1.0, compression="gz", directory=None,
                 **kwargs):
        super(SnapshotterBase, self).__init__(workflow, **kwargs)
        self.prefix = prefix
        self.interval = interval
        self.time_interval = time_interval
        self.compression = compression
        self.directory = directory
        self.decision = None   # optional: gate on .improved
        self.suffix = ""
        self.destination = None
        self._skipped = 0
        self._last_time = 0.0

    def initialize(self, **kwargs):
        super(SnapshotterBase, self).initialize(**kwargs)
        if self.directory is None:
            self.directory = root.common.dirs.get("snapshots", "snapshots")
        if not self.suffix:
            # ensemble/genetics instances disambiguate their snapshot
            # files through this config key
            self.suffix = root.common.get("snapshot_suffix", "")
        os.makedirs(self.directory, exist_ok=True)
        self._last_time = time.time()

    def run(self):
        if self.decision is not None and not self.decision.improved:
            return
        self._skipped += 1
        if self._skipped < self.interval:
            return
        if time.time() - self._last_time < self.time_interval:
            return
        self._skipped = 0
        self._last_time = time.time()
        self.export()

    def export(self):
        raise NotImplementedError()


class SnapshotterToFile(SnapshotterBase):
    """Pickle to file with codec + ``_current`` symlink
    (ref: snapshotter.py:360-426)."""

    def export(self):
        target = self.workflow
        name = "%s%s%s" % (self.prefix,
                           ("_" + self.suffix) if self.suffix else "",
                           EXT[self.compression])
        path = os.path.join(self.directory, name)
        with self.timed_event("snapshot"):
            try:
                with CODECS[self.compression](path, "w") as f:
                    pickle.dump(target, f,
                                protocol=pickle.HIGHEST_PROTOCOL)
            except Exception:  # any failure class — diagnose, then re-raise
                # name the offending attribute path, not just the
                # innermost type (ref: pickle2.py debug hooks)
                from veles_tpu.pickle_debug import explain_pickle_failure
                explain_pickle_failure(target, logger=self)
                raise
        self.destination = path
        size = os.path.getsize(path)
        self.info("snapshot -> %s (%.1f MiB)", path, size / 2 ** 20)
        current = os.path.join(self.directory,
                               "%s_current%s" % (self.prefix,
                                                 EXT[self.compression]))
        try:
            if os.path.islink(current) or os.path.exists(current):
                os.unlink(current)
            os.symlink(os.path.basename(path), current)
        except OSError:
            pass

    @staticmethod
    def import_file(path, weights_dtype=None):
        """Load a snapshot back into a live workflow
        (ref: snapshotter.py:411-420 + __main__.py:539-589).

        ``weights_dtype="int8"`` quantizes every unit exposing
        ``quantize_weights`` (the transformer blocks) AT LOAD TIME:
        the f32 checkpoint stays on disk untouched, the resident
        copy holds int8 weights + per-output-column scales — weight
        HBM halves before the first upload ever happens.  Serving
        quality rides the weight_quant gate
        (serving/kv_quality.weight_quant_quality)."""
        if weights_dtype not in (None, "fp32", "int8"):
            raise ValueError(
                "weights_dtype must be fp32 or int8, got %r"
                % (weights_dtype,))
        for codec, ext in EXT.items():
            if path.endswith(ext) and ext != ".pickle":
                opener = CODECS[codec]
                break
        else:
            opener = CODECS[None]
        with opener(path, "r") as f:
            obj = pickle.load(f)
        obj._restored_from_snapshot_ = True
        if weights_dtype == "int8":
            for unit in getattr(obj, "units", ()):
                if hasattr(unit, "quantize_weights"):
                    unit.quantize_weights()
        return obj


class SnapshotterToDB(SnapshotterBase):
    """Database-backed snapshot store (ref: snapshotter.py:428-518 — the
    reference spoke ODBC).  DB-API backends: ``sqlite:<path>`` (stdlib,
    the tested default) or an ODBC connection string via pyodbc when
    installed.  The table name is validated as an identifier (it cannot
    ride a parameter marker in DDL)."""

    def __init__(self, workflow, odbc=None, table="veles", **kwargs):
        super(SnapshotterToDB, self).__init__(workflow, **kwargs)
        self.odbc = odbc
        if not table.isidentifier():
            raise ValueError("table %r is not a valid identifier" % table)
        self.table = table

    def init_unpickled(self):
        super(SnapshotterToDB, self).init_unpickled()
        self._conn_ = None

    @staticmethod
    def _connect(dsn):
        if dsn.startswith("sqlite:"):
            import sqlite3
            return sqlite3.connect(dsn[len("sqlite:"):])
        import pyodbc
        return pyodbc.connect(dsn)

    def initialize(self, **kwargs):
        super(SnapshotterToDB, self).initialize(**kwargs)
        self._ensure_conn()

    def _ensure_conn(self):
        if self._conn_ is None:
            self._conn_ = self._connect(self.odbc)
            if self.odbc.startswith("sqlite:"):
                ddl = ("CREATE TABLE IF NOT EXISTS %s (id INTEGER "
                       "PRIMARY KEY, prefix TEXT, ts TIMESTAMP, "
                       "blob BLOB)")
            else:  # Postgres-over-ODBC, the reference's deployment
                ddl = ("CREATE TABLE IF NOT EXISTS %s (id SERIAL "
                       "PRIMARY KEY, prefix TEXT, ts TIMESTAMP, "
                       "blob BYTEA)")
            cur = self._conn_.cursor()
            cur.execute(ddl % self.table)
            self._conn_.commit()

    def export(self):
        self._ensure_conn()
        blob = self._codec_dump(self.workflow)
        cur = self._conn_.cursor()
        cur.execute(
            "INSERT INTO %s (prefix, ts, blob) VALUES (?, "
            "CURRENT_TIMESTAMP, ?)" % self.table, (self.prefix, blob))
        self._conn_.commit()
        self.destination = "db:%s/%s" % (self.table, self.prefix)
        self.info("snapshot -> %s (%.1f MiB)", self.destination,
                  len(blob) / 2 ** 20)

    _DB_CODECS = {None: lambda b: b, "": lambda b: b,
                  "gz": lambda b: gzip.compress(b, 1),
                  "bz2": lambda b: bz2.compress(b),
                  "xz": lambda b: lzma.compress(b)}

    def _codec_dump(self, obj):
        raw = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        try:
            return self._DB_CODECS[self.compression](raw)
        except KeyError:
            raise ValueError("unsupported DB snapshot codec %r"
                             % self.compression)

    @classmethod
    def import_db(cls, dsn, table="veles", prefix=None):
        """Load the newest snapshot (optionally for one prefix) back
        into a live workflow (ref resume path: __main__.py:539-589)."""
        if not table.isidentifier():
            raise ValueError("table %r is not a valid identifier" % table)
        conn = cls._connect(dsn)
        try:
            cur = conn.cursor()
            if prefix is not None:
                cur.execute(
                    "SELECT blob FROM %s WHERE prefix = ? "
                    "ORDER BY id DESC LIMIT 1" % table, (prefix,))
            else:
                cur.execute("SELECT blob FROM %s ORDER BY id DESC "
                            "LIMIT 1" % table)
            row = cur.fetchone()
        finally:
            conn.close()
        if row is None:
            raise KeyError("no snapshot in %s" % table)
        blob = bytes(row[0])
        if blob[:2] == b"\x1f\x8b":
            blob = gzip.decompress(blob)
        elif blob[:3] == b"BZh":
            blob = bz2.decompress(blob)
        elif blob[:6] == b"\xfd7zXZ\x00":
            blob = lzma.decompress(blob)
        obj = pickle.loads(blob)
        try:
            obj._restored_from_snapshot_ = True
        except AttributeError:  # plain payloads (no attr dict)
            pass
        return obj


def Snapshotter(workflow, odbc=None, **kwargs):
    """Facade choosing the backend (ref: snapshotter.py:522)."""
    if odbc:
        return SnapshotterToDB(workflow, odbc=odbc, **kwargs)
    return SnapshotterToFile(workflow, **kwargs)
