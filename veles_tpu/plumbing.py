"""Plumbing units: StartPoint, EndPoint, Repeater, Fork/Join helpers
(ref: veles/plumbing.py:17-60)."""

from veles_tpu.units import Unit


class StartPoint(Unit):
    """Workflow entry node; firing it starts a graph wave."""

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("name", "Start")
        super(StartPoint, self).__init__(workflow, **kwargs)


class EndPoint(Unit):
    """Workflow exit node; running it finishes the workflow run."""

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("name", "End")
        super(EndPoint, self).__init__(workflow, **kwargs)

    def run(self):
        self.workflow.on_workflow_finished()

    def run_dependent(self):
        pass  # nothing runs after the end


class Repeater(Unit):
    """Loop head: fires on ANY incoming signal (start edge or loop-back
    edge), unlike the default all-inputs gate — this is what makes training
    loops expressible in the graph (ref: veles/plumbing.py, Repeater)."""

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("name", "Repeater")
        super(Repeater, self).__init__(workflow, **kwargs)

    def open_gate(self, src):
        for k in self.links_from:
            self.links_from[k] = False
        return True
