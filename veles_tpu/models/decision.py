"""DecisionGD + Rollback — training control (reconstruction of znicz
decision.py / rollback.py; extras item 11).

DecisionGD accumulates per-class error counts over each epoch, tracks
the best validation error, raises ``improved`` when a new minimum lands
(the snapshotter gates on it) and ``complete`` when validation stopped
improving for ``fail_iterations`` epochs or ``max_epochs`` passed (the
workflow's end gate).

Rollback keeps a host-side copy of the best parameters; on plateau it
restores them and scales the trainer's learning rate.
"""

import numpy

from veles_tpu.loader.base import CLASS_NAME, TEST, TRAIN, VALID
from veles_tpu.mutable import Bool
from veles_tpu.result_provider import IResultProvider
from veles_tpu.units import Unit


class DecisionGD(Unit, IResultProvider):
    """Stopping / bookkeeping logic (znicz decision.DecisionGD)."""

    VIEW_GROUP = "PLUMBING"

    def __init__(self, workflow, fail_iterations=100, max_epochs=None,
                 **kwargs):
        super(DecisionGD, self).__init__(workflow, **kwargs)
        self.fail_iterations = fail_iterations
        self.max_epochs = max_epochs
        self.loader = None
        self.trainer = None      # supplies n_err/loss Arrays
        self.complete = Bool(False, "complete")
        self.improved = Bool(False, "improved")
        self.epoch_n_err = [0, 0, 0]
        self.epoch_samples = [0, 0, 0]
        self.epoch_loss_sum = [0.0, 0.0, 0.0]
        self.epoch_metrics = {}
        self.min_validation_n_err = None
        self.min_validation_n_err_epoch = -1
        self.best_train_n_err = None
        #: master-side epoch counter — with several async workers the
        #: loader's serve-time flags are not observable at update-apply
        #: time, so the master counts epochs by applied sample totals
        self._master_epoch = 0
        self.demand("loader", "trainer")

    @property
    def effective_epoch(self):
        return self._master_epoch if self.is_master \
            else self.loader.epoch_number

    def _loss_driven(self):
        from veles_tpu.models.evaluator import EvaluatorMSE
        ev = getattr(self.trainer, "evaluator", None)
        return isinstance(ev, EvaluatorMSE)

    @property
    def validation_error_pct(self):
        """Last closed epoch's validation error % (plotter feed)."""
        return self.epoch_metrics.get("validation_error_pct")

    @property
    def fail_count(self):
        return (self.effective_epoch -
                max(self.min_validation_n_err_epoch, 0))

    def run(self):
        """Per-minibatch accounting stays ON DEVICE (trainer.epoch_acc);
        this unit syncs with the device only at epoch boundaries — the
        per-step host read the reference did (znicz decision) would
        serialize every dispatch."""
        if self.is_slave:
            # one job = one minibatch wave: close the loop gate so
            # do_job's run() returns; epoch accounting happens on the
            # master from the acc deltas workers send (znicz decision
            # behaved the same way on slaves)
            self.complete.set(True)
            if self._workflow is not None:
                self._workflow.on_workflow_finished()
            return
        self._evaluate_epoch()

    def _evaluate_epoch(self):
        l = self.loader
        self.improved.set(False)
        if l.epoch_ended:
            self._close_eval_epoch()
        if l.train_ended:
            self._close_train_epoch()

    def _close_eval_epoch(self):
        """Read + reset the TEST/VALID accumulator rows and evaluate the
        epoch (shared by the standalone and master paths)."""
        acc = self.trainer.read_epoch_acc(reset_classes=(TEST, VALID))
        for cls in (TEST, VALID):
            n_err, loss_sum, samples = acc[cls]
            self.epoch_n_err[cls] = int(n_err)
            self.epoch_samples[cls] = int(samples)
            self.epoch_loss_sum[cls] = loss_sum
        self._on_epoch_ended()

    def _close_train_epoch(self):
        acc = self.trainer.read_epoch_acc(reset_classes=(TRAIN,))
        n_err, loss_sum, samples = acc[TRAIN]
        self.epoch_n_err[TRAIN] = int(n_err)
        self.epoch_samples[TRAIN] = int(samples)
        self.epoch_loss_sum[TRAIN] = loss_sum
        if self.is_master:
            self._master_epoch += 1
        self._maybe_complete()
        self.epoch_n_err[TRAIN] = 0
        self.epoch_samples[TRAIN] = 0
        self.epoch_loss_sum[TRAIN] = 0.0

    def _error_pct(self, cls):
        n = self.epoch_samples[cls]
        return 100.0 * self.epoch_n_err[cls] / n if n else 0.0

    def _on_epoch_ended(self):
        l = self.loader
        for cls in (TEST, VALID):
            if self.epoch_samples[cls]:
                self.epoch_metrics["%s_error_pct" % CLASS_NAME[cls]] = \
                    self._error_pct(cls)
                self.epoch_metrics["%s_loss" % CLASS_NAME[cls]] = \
                    self.epoch_loss_sum[cls] / self.epoch_samples[cls]
        cls = VALID if self.epoch_samples[VALID] else TEST
        n_err = self.epoch_n_err[cls]
        loss = self.epoch_loss_sum[cls] / max(self.epoch_samples[cls], 1)
        # MSE workflows carry no n_err signal — improvement is tracked on
        # the validation loss instead (znicz decision tracked epoch_metrics
        # per evaluator kind)
        metric = loss if self._loss_driven() else n_err
        # loss-history divergence detection (EMA + patience) feeds the
        # health monitor; a 'halt' verdict ends the run gracefully at
        # this epoch boundary instead of burning chips on a diverged
        # model (telemetry/health.py)
        from veles_tpu.telemetry import health as health_lib
        if health_lib.health_config()["enabled"]:
            verdict = health_lib.monitor.observe_loss(loss)
            if verdict == "halt":
                self.warning(
                    "health policy 'halt': validation loss diverged "
                    "- stopping")
                self.complete.set(True)
        if self.min_validation_n_err is None \
                or metric < self.min_validation_n_err:
            self.min_validation_n_err = metric
            self.min_validation_n_err_epoch = self.effective_epoch
            self.improved.set(True)
        self.info(
            "epoch %d: validation err %.2f%% (best %s @ epoch %d), "
            "val loss %.4f",
            self.effective_epoch, self._error_pct(VALID),
            self.min_validation_n_err, self.min_validation_n_err_epoch,
            self.epoch_metrics.get("validation_loss", float("nan")))
        self._maybe_complete()
        for cls in (TEST, VALID):
            self.epoch_n_err[cls] = 0
            self.epoch_samples[cls] = 0
            self.epoch_loss_sum[cls] = 0.0

    def _maybe_complete(self):
        if self.max_epochs is not None \
                and self.effective_epoch >= self.max_epochs:
            self.complete.set(True)
        if self.min_validation_n_err is not None \
                and self.fail_count > self.fail_iterations:
            self.info("no improvement for %d epochs — stopping",
                      self.fail_iterations)
            self.complete.set(True)
        if self.complete and self._workflow is not None:
            self._workflow.on_workflow_finished()

    # -- elastic DCN sync: the master evaluates epochs as worker updates
    #    land (its graph never runs); workers just reset their loop gate --

    negotiates_on_connect = True

    def generate_data_for_slave(self, slave=None):
        return True  # presence alone triggers the worker-side reset

    def apply_data_from_master(self, data):
        self.complete.set(False)

    def generate_data_for_master(self):
        return True

    def apply_data_from_slave(self, data, slave=None):
        """Master: with several async workers the loader's serve-time
        flags aren't observable here (another worker may already hold
        next-epoch jobs), so epochs complete when the *applied* sample
        totals in the trainer's accumulator reach the class lengths
        (the reference master was equally asynchronous about it)."""
        l = self.loader
        acc = self.trainer.read_epoch_acc()
        self.improved.set(False)
        # every eval class present in the dataset must be fully applied
        # before the epoch closes — gating on VALID alone would let a
        # slow worker's in-flight TEST minibatch leak into the next epoch
        eval_classes = [c for c in (TEST, VALID) if l.class_lengths[c]]
        if eval_classes and all(
                acc[c][2] >= l.class_lengths[c] for c in eval_classes):
            self._close_eval_epoch()
        train_needed = l.effective_total_samples - l.class_end_offsets[VALID]
        if train_needed and acc[TRAIN][2] >= train_needed:
            self._close_train_epoch()

    def drop_slave(self, slave=None):
        pass

    def get_metric_values(self):
        out = dict(self.epoch_metrics)
        if self.min_validation_n_err is not None:
            out["min_validation_n_err"] = self.min_validation_n_err
            out["min_validation_n_err_epoch"] = \
                self.min_validation_n_err_epoch
        return out


class Rollback(Unit):
    """Best-state keeper (znicz rollback; extras item 11): saves params
    on improvement; after ``fail_iterations`` epochs without improvement
    restores them and multiplies the trainer's learning rate by
    ``lr_plus``."""

    VIEW_GROUP = "SERVICE"

    def __init__(self, workflow, fail_iterations=10, lr_plus=0.5, **kwargs):
        super(Rollback, self).__init__(workflow, **kwargs)
        self.fail_iterations = fail_iterations
        self.lr_plus = lr_plus
        self.decision = None
        self.trainer = None
        self.saved_params = None
        self.saved_opt_state = None
        self._last_restore_epoch = -1
        self.demand("decision", "trainer")

    def run(self):
        d = self.decision
        if d.improved:
            self.save()
        elif (self.saved_params is not None
              and d.loader.epoch_ended
              and d.fail_count and d.fail_count % self.fail_iterations == 0
              and d.loader.epoch_number != self._last_restore_epoch):
            self.restore()
            self._last_restore_epoch = d.loader.epoch_number

    def save(self):
        params = {}
        for i, u in enumerate(self.trainer.forwards):
            params[i] = {}
            for name, arr in u.param_arrays().items():
                arr.map_read()
                params[i][name] = numpy.array(arr.mem)
        # solver state (momentum/Adam moments) belongs to the trajectory:
        # restoring weights under stale velocity would immediately push
        # them back toward the diverged region
        opt = {}
        for i, layer in self.trainer.opt_state.items():
            opt[i] = {}
            for name, slots in layer.items():
                opt[i][name] = {}
                for s, arr in slots.items():
                    arr.map_read()
                    opt[i][name][s] = numpy.array(arr.mem)
        self.saved_params = params
        self.saved_opt_state = opt

    def restore(self):
        self.info("rolling back to best params; lr *= %s", self.lr_plus)
        for i, u in enumerate(self.trainer.forwards):
            for name, arr in u.param_arrays().items():
                arr.map_invalidate()
                arr.mem[...] = self.saved_params[i][name]
                arr.unmap()
        for i, layer in self.trainer.opt_state.items():
            for name, slots in layer.items():
                for s, arr in slots.items():
                    arr.map_invalidate()
                    arr.mem[...] = self.saved_opt_state[i][name][s]
                    arr.unmap()
        self.trainer.lr_multiplier *= self.lr_plus
