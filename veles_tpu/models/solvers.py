"""Gradient-descent solvers (surface per manualrst_veles_algorithms.rst:
"Stochastic gradient descent solver with momentum", "AdaGrad/AdaDelta
solvers", plus Adam as the modern default the reference predates).

Each solver is a pair of pure functions over one parameter tensor:

- ``init(param) -> state`` (dict of tensors)
- ``update(param, grad, state, hp) -> (new_param, new_state)``

``hp`` carries ``lr``, ``decay`` (L2+L1 per ``l1_vs_l2``) and
``moment`` — resolved per layer (extras item 13).  Weight decay is
applied as in the reference: the decay term joins the gradient before
the solver step.
"""

import jax.numpy as jnp


def _decayed_grad(param, grad, hp):
    """grad + weights_decay * d/dw (l2/l1 mix)
    (znicz gradient_descent weights_decay + l1_vs_l2 surface)."""
    decay = hp.get("decay", 0.0)
    l1_vs_l2 = hp.get("l1_vs_l2", 0.0)
    if decay:
        reg = l1_vs_l2 * jnp.sign(param) + (1.0 - l1_vs_l2) * param
        grad = grad + decay * reg
    return grad


class SGD:
    """Plain / momentum SGD (znicz GradientDescent solver)."""

    name = "sgd"

    @staticmethod
    def init(param):
        return {"v": jnp.zeros_like(param)}

    @staticmethod
    def update(param, grad, state, hp):
        grad = _decayed_grad(param, grad, hp)
        v = hp.get("moment", 0.0) * state["v"] - hp["lr"] * grad
        return param + v, {"v": v}


class AdaGrad:
    name = "adagrad"
    EPS = 1e-8

    @staticmethod
    def init(param):
        return {"g2": jnp.zeros_like(param)}

    @staticmethod
    def update(param, grad, state, hp):
        grad = _decayed_grad(param, grad, hp)
        g2 = state["g2"] + grad * grad
        step = hp["lr"] * grad / (jnp.sqrt(g2) + AdaGrad.EPS)
        return param - step, {"g2": g2}


class AdaDelta:
    name = "adadelta"
    RHO = 0.95
    EPS = 1e-6

    @staticmethod
    def init(param):
        return {"g2": jnp.zeros_like(param), "x2": jnp.zeros_like(param)}

    @staticmethod
    def update(param, grad, state, hp):
        grad = _decayed_grad(param, grad, hp)
        rho, eps = AdaDelta.RHO, AdaDelta.EPS
        g2 = rho * state["g2"] + (1 - rho) * grad * grad
        dx = -jnp.sqrt(state["x2"] + eps) / jnp.sqrt(g2 + eps) * grad
        x2 = rho * state["x2"] + (1 - rho) * dx * dx
        # lr acts as a scale on the adapted step (1.0 = classic AdaDelta)
        return param + hp["lr"] * dx, {"g2": g2, "x2": x2}


class Adam:
    name = "adam"
    B1 = 0.9
    B2 = 0.999
    EPS = 1e-8

    @staticmethod
    def init(param):
        return {"m": jnp.zeros_like(param), "v": jnp.zeros_like(param),
                "t": jnp.zeros((), jnp.float32)}

    @staticmethod
    def update(param, grad, state, hp):
        grad = _decayed_grad(param, grad, hp)
        b1, b2, eps = Adam.B1, Adam.B2, Adam.EPS
        t = state["t"] + 1
        m = b1 * state["m"] + (1 - b1) * grad
        v = b2 * state["v"] + (1 - b2) * grad * grad
        mhat = m / (1 - b1 ** t)
        vhat = v / (1 - b2 ** t)
        return (param - hp["lr"] * mhat / (jnp.sqrt(vhat) + eps),
                {"m": m, "v": v, "t": t})


SOLVERS = {c.name: c for c in (SGD, AdaGrad, AdaDelta, Adam)}


def get_solver(name):
    if isinstance(name, type):
        return name
    try:
        return SOLVERS[name]
    except KeyError:
        raise KeyError("unknown solver %r (have: %s)"
                       % (name, sorted(SOLVERS)))
