"""Standard workflow builders (seed of the znicz StandardWorkflow
surface): one call wires loader → forward layers → evaluator → trainer.

Used by samples, bench, and the driver entry points so the unit
handshake lives in exactly one place.
"""

from veles_tpu.accelerated_units import AcceleratedWorkflow
from veles_tpu.models.all2all import All2AllSoftmax, All2AllTanh
from veles_tpu.models.evaluator import EvaluatorSoftmax
from veles_tpu.models.gd import GradientDescent


def build_mlp_classifier(device, loader, hidden=(100,), classes=10,
                         mesh=None, workflow=None, name="mlp",
                         hidden_cls=All2AllTanh, **gd_kwargs):
    """loader (already constructed, not yet initialized) →
    tanh hidden layers → softmax head → evaluator → fused trainer.

    Returns (workflow, layers, evaluator, trainer)."""
    wf = workflow or AcceleratedWorkflow(None, name=name)
    loader.initialize(device=device)
    layers = []
    prev_out = loader.minibatch_data
    for li, width in enumerate(hidden):
        u = hidden_cls(wf, output_sample_shape=(width,),
                       name="fc%d" % li)
        u.input = prev_out
        u.initialize(device=device)
        layers.append(u)
        prev_out = u.output
    head = All2AllSoftmax(wf, output_sample_shape=(classes,), name="head")
    head.input = prev_out
    head.initialize(device=device)
    layers.append(head)
    ev = EvaluatorSoftmax(wf, name="evaluator")
    ev.output = head.output
    ev.labels = loader.minibatch_labels
    ev.loader = loader
    ev.initialize(device=device)
    gd_kwargs.setdefault("solver", "sgd")
    gd_kwargs.setdefault("learning_rate", 0.05)
    gd = GradientDescent(wf, forwards=layers, evaluator=ev,
                         loader=loader, mesh=mesh, name="gd", **gd_kwargs)
    gd.initialize(device=device)
    return wf, layers, ev, gd
