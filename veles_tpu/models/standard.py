"""Standard workflow builders (reconstruction of the znicz
StandardWorkflow surface, manualrst_veles_algorithms.rst: models are
described by a ``layers`` list of type+kwargs dicts).

Two entry points:

- :func:`build_mlp_classifier` — imperative wiring for simple MLPs
  (bench / driver entry points);
- :class:`StandardWorkflow` — the config-driven graph the samples use:
  ``layers=[{"type": "conv_relu", "n_kernels": 32, ...}, ...]`` builds
  the full train graph (repeater → loader → trainer → decision →
  snapshotter, loop + end gates) in one unit.

Layer spec keys: ``type`` (see :data:`LAYER_TYPES`); ``"->"`` merges
extra forward kwargs; ``"<-"`` merges per-layer trainer hyper-parameter
overrides (extras item 13) — both znicz conventions.
"""

from veles_tpu.accelerated_units import AcceleratedWorkflow
from veles_tpu.models.attention import MultiHeadAttention
from veles_tpu.models.embedding import Embedding
from veles_tpu.models.moe import MoE
from veles_tpu.models.transformer import MeanPoolSeq, TransformerBlock, TokenProjection
from veles_tpu.models.all2all import (
    All2All, All2AllRELU, All2AllSigmoid, All2AllSoftmax,
    All2AllStrictRELU, All2AllTanh)
from veles_tpu.models.conv import (
    Conv, ConvRELU, ConvStrictRELU, ConvTanh, Deconv)
from veles_tpu.models.dropout import DropoutForward
from veles_tpu.models.evaluator import EvaluatorMSE, EvaluatorSoftmax
from veles_tpu.models.gd import GradientDescent
from veles_tpu.models.lrn import LRNormalizerForward
from veles_tpu.models.pooling import AvgPooling, Depooling, MaxPooling
from veles_tpu.models.recurrent import LSTM, LastTimestep, SimpleRNN

#: znicz layer-type names → forward unit classes
LAYER_TYPES = {
    "all2all": All2All,
    "all2all_tanh": All2AllTanh,
    "all2all_relu": All2AllRELU,
    "all2all_str": All2AllStrictRELU,
    "all2all_sigmoid": All2AllSigmoid,
    "softmax": All2AllSoftmax,
    "conv": Conv,
    "conv_tanh": ConvTanh,
    "conv_relu": ConvRELU,
    "conv_str": ConvStrictRELU,
    "deconv": Deconv,
    "max_pooling": MaxPooling,
    "avg_pooling": AvgPooling,
    "depooling": Depooling,
    "dropout": DropoutForward,
    "norm": LRNormalizerForward,
    "attention": MultiHeadAttention,
    "moe": MoE,
    "embedding": Embedding,
    "transformer_block": TransformerBlock,
    "mean_pool_seq": MeanPoolSeq,
    "rnn": SimpleRNN,
    "lstm": LSTM,
    "last_timestep": LastTimestep,
    "token_logits": TokenProjection,
}


def make_forwards(workflow, input_array, layers):
    """Instantiate the forward chain from a znicz-style ``layers`` spec;
    returns the unit list (uninitialized — the workflow's dependency-
    ordered initialize fills parameters)."""
    units = []
    prev = input_array
    for i, spec in enumerate(dict(s) for s in layers):
        ltype = spec.pop("type")
        kwargs = dict(spec.pop("->", {}))
        kwargs.update(spec.pop("<-", {}))
        kwargs.update(spec)
        cls = LAYER_TYPES[ltype]
        u = cls(workflow, name="%s%d" % (ltype, i), **kwargs)
        u.input = prev
        prev = u.output
        units.append(u)
    return units


def build_mlp_classifier(device, loader, hidden=(100,), classes=10,
                         mesh=None, workflow=None, name="mlp",
                         hidden_cls=All2AllTanh, **gd_kwargs):
    """loader (already constructed, not yet initialized) →
    tanh hidden layers → softmax head → evaluator → fused trainer.

    Returns (workflow, layers, evaluator, trainer)."""
    wf = workflow or AcceleratedWorkflow(None, name=name)
    loader.initialize(device=device)
    layers = []
    prev_out = loader.minibatch_data
    for li, width in enumerate(hidden):
        u = hidden_cls(wf, output_sample_shape=(width,),
                       name="fc%d" % li)
        u.input = prev_out
        u.initialize(device=device)
        layers.append(u)
        prev_out = u.output
    head = All2AllSoftmax(wf, output_sample_shape=(classes,), name="head")
    head.input = prev_out
    head.initialize(device=device)
    layers.append(head)
    ev = EvaluatorSoftmax(wf, name="evaluator")
    ev.output = head.output
    ev.labels = loader.minibatch_labels
    ev.loader = loader
    ev.initialize(device=device)
    gd_kwargs.setdefault("solver", "sgd")
    gd_kwargs.setdefault("learning_rate", 0.05)
    gd = GradientDescent(wf, forwards=layers, evaluator=ev,
                         loader=loader, mesh=mesh, name="gd", **gd_kwargs)
    gd.initialize(device=device)
    return wf, layers, ev, gd


class StandardWorkflow(AcceleratedWorkflow):
    """The config-driven training graph (znicz StandardWorkflow role).

    Parameters mirror the znicz config surface:

    - ``loader_factory(workflow, **loader_config)`` builds the loader
      (or pass a ready ``loader`` instance);
    - ``layers`` — the forward-chain spec (see :func:`make_forwards`);
    - ``loss`` — "softmax" | "mse" | "next_token" selects the
      evaluator (next_token: per-token LM cross-entropy against the
      input shifted by one — EvaluatorNextToken);
    - ``decision_config`` / ``snapshotter_config`` / trainer kwargs.
    """

    def __init__(self, workflow, loader_factory=None, loader=None,
                 loader_config=None, layers=(), loss="softmax",
                 decision_config=None, snapshotter_config=None,
                 mesh=None, name="StandardWorkflow", plotters=True,
                 **trainer_kwargs):
        from veles_tpu.models.decision import DecisionGD
        from veles_tpu.plumbing import Repeater
        from veles_tpu.snapshotter import Snapshotter

        if mesh is None:
            # every config-driven sample honours the generic mesh knob:
            # -c "root.common.mesh = {'dp': -1}" shards ANY standard
            # workflow without sample-specific plumbing
            from veles_tpu.config import root
            raw = root.common.get_dict("mesh")
            if raw:
                from veles_tpu.parallel import build_mesh
                mesh = build_mesh(raw)

        super(StandardWorkflow, self).__init__(workflow, name=name)
        self.repeater = Repeater(self)
        self.repeater.link_from(self.start_point)

        if loader is None:
            loader = loader_factory(self, **(loader_config or {}))
        self.loader = loader
        self.loader.link_from(self.repeater)

        self.forwards = make_forwards(
            self, self.loader.minibatch_data, layers)

        if loss == "mse":
            self.evaluator = EvaluatorMSE(self)
            self.evaluator.target = self.loader.minibatch_targets
        elif loss == "next_token":
            from veles_tpu.models.evaluator import EvaluatorNextToken
            self.evaluator = EvaluatorNextToken(self)
            self.evaluator.tokens = self.loader.minibatch_data
        else:
            self.evaluator = EvaluatorSoftmax(self)
            self.evaluator.labels = self.loader.minibatch_labels
            if isinstance(self.forwards[-1], All2AllSoftmax):
                # exact in-graph loss from the head's real logits
                self.evaluator.logits = self.forwards[-1].logits_out
        self.evaluator.output = self.forwards[-1].output
        self.evaluator.loader = self.loader

        self.gd = GradientDescent(
            self, forwards=self.forwards, evaluator=self.evaluator,
            loader=self.loader, mesh=mesh, **trainer_kwargs)
        self.gd.link_from(self.loader)

        self.decision = DecisionGD(self, **(decision_config or {}))
        self.decision.loader = self.loader
        self.decision.trainer = self.gd
        self.decision.link_from(self.gd)

        snapshotter_config = dict(snapshotter_config or {})
        if snapshotter_config.pop("enabled", True):
            self.snapshotter = Snapshotter(self, **snapshotter_config)
            self.snapshotter.decision = self.decision
            self.snapshotter.link_from(self.decision)
        else:
            self.snapshotter = None

        # live plots (ref: znicz StandardWorkflow wired its plotter set
        # the same way); payloads publish only when a graphics server or
        # web-status notifier is attached
        self.plotters = []
        if plotters:
            from veles_tpu.plotting_units import AccumulatingPlotter
            err_plot = AccumulatingPlotter(
                self, obj=self.decision, attr="validation_error_pct",
                label="validation error", ylabel="%",
                name="error_curve")
            err_plot.gate_skip = ~self.loader.epoch_ended
            loss_plot = AccumulatingPlotter(
                self, obj=self.gd, attr="loss", label="train loss",
                ylabel="loss", name="loss_curve")
            for plot in (err_plot, loss_plot):
                plot.link_from(self.decision)
                self.plotters.append(plot)

        self.repeater.link_from(self.decision)
        self.loader.gate_block = self.decision.complete
        self.end_point.link_from(self.decision)
        self.end_point.gate_block = ~self.decision.complete
