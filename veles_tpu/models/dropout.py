"""Dropout (reconstruction of znicz dropout; extras item 2).

In the trainer's fused program the mask is drawn from a traced key
(:meth:`DropoutForward.apply_train`); the in-graph forward step is
identity scaled for inference, matching the reference's
forward-vs-training split.
"""

import jax
import jax.numpy as jnp
import numpy

from veles_tpu.memory import Array
from veles_tpu.models.nn_units import ForwardBase
from veles_tpu.units import MissingDemand


class DropoutForward(ForwardBase):
    """znicz dropout.DropoutForward: ``dropout_ratio`` of inputs zeroed
    during training; inference passes through unscaled (inverted dropout
    scales at train time)."""

    PARAMS = ()

    def __init__(self, workflow, dropout_ratio=0.5, **kwargs):
        super(DropoutForward, self).__init__(workflow, **kwargs)
        self.dropout_ratio = float(dropout_ratio)

    def fill_params(self):
        pass

    def output_shape_for(self, input_shape):
        return input_shape

    def apply(self, params, x):
        # inference path: identity (inverted dropout)
        return x

    def export_config(self):
        return {"dropout_ratio": self.dropout_ratio}

    def apply_train(self, params, x, key):
        keep = 1.0 - self.dropout_ratio
        mask = jax.random.bernoulli(key, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0)
