"""Restricted Boltzmann Machine (manualrst_veles_algorithms.rst
"Restricted Boltzmann Machine": the reference's units were numpy-only
with an untested workflow; these are live and tested).

Bernoulli-Bernoulli RBM with CD-k training — the whole contrastive-
divergence step (Gibbs chain + parameter update) is one jitted program.
"""

import jax
import jax.numpy as jnp
import numpy

from veles_tpu.accelerated_units import AcceleratedUnit
from veles_tpu.memory import Array
from veles_tpu.units import MissingDemand
from veles_tpu import prng as prng_mod


class BernoulliRBM(AcceleratedUnit):
    """RBM unit: ``run()`` performs one CD-k update on the loader's
    minibatch; ``hidden_probs(v)`` / ``reconstruct(v)`` are the
    inference surfaces."""

    FUSABLE = False

    def __init__(self, workflow, loader=None, hidden=64, cd_k=1,
                 learning_rate=0.1, prng_key="rbm", **kwargs):
        super(BernoulliRBM, self).__init__(workflow, **kwargs)
        self.loader = loader
        self.hidden = int(hidden)
        self.cd_k = int(cd_k)
        self.learning_rate = float(learning_rate)
        self.prng = prng_mod.get(prng_key)
        self.weights = Array()   # [visible, hidden]
        self.vbias = Array()
        self.hbias = Array()
        self.recon_error = Array()
        self.global_step = 0
        self.demand("loader")

    def init_unpickled(self):
        super(BernoulliRBM, self).init_unpickled()
        self._step_ = None

    def initialize(self, device=None, **kwargs):
        if self.loader is None:
            raise MissingDemand(self, {"loader"})
        visible = int(numpy.prod(self.loader.minibatch_data.shape[1:]))
        if not bool(self.weights):
            w = numpy.zeros((visible, self.hidden), numpy.float32)
            self.prng.fill_normal(w, 0.0, 0.01)
            self.weights.reset(w)
            self.vbias.reset(numpy.zeros((visible,), numpy.float32))
            self.hbias.reset(numpy.zeros((self.hidden,), numpy.float32))
        self.recon_error.reset(numpy.zeros((), numpy.float32))
        super(BernoulliRBM, self).initialize(device=device, **kwargs)

    # -- inference -------------------------------------------------------------

    def hidden_probs(self, v, params=None):
        w, _, hb = self._params_of(params)
        return jax.nn.sigmoid(v @ w + hb)

    def reconstruct(self, v, params=None):
        w, vb, _ = self._params_of(params)
        h = self.hidden_probs(v, params)
        return jax.nn.sigmoid(h @ w.T + vb)

    def _params_of(self, params):
        if params is not None:
            return params["weights"], params["vbias"], params["hbias"]
        return (self.weights.devmem, self.vbias.devmem,
                self.hbias.devmem)

    # -- CD-k training ---------------------------------------------------------

    def _build_step(self):
        k = self.cd_k
        lr = self.learning_rate

        def step(w, vb, hb, v0, size, key):
            mask = (jnp.arange(v0.shape[0]) < size).astype(
                jnp.float32)[:, None]
            v0 = v0.reshape(v0.shape[0], -1) * mask
            h0p = jax.nn.sigmoid(v0 @ w + hb)

            def gibbs(carry, kk):
                hp, _ = carry
                sub = jax.random.fold_in(key, kk)
                h = jax.random.bernoulli(sub, hp).astype(v0.dtype)
                vp = jax.nn.sigmoid(h @ w.T + vb)
                hp2 = jax.nn.sigmoid(vp @ w + hb)
                return (hp2, vp), None

            (hkp, vk), _ = jax.lax.scan(
                gibbs, (h0p, v0), jnp.arange(k))
            n = jnp.maximum(jnp.sum(mask), 1.0)
            pos = v0.T @ h0p
            neg = (vk * mask).T @ hkp
            w = w + lr * (pos - neg) / n
            vb = vb + lr * jnp.sum((v0 - vk * mask), axis=0) / n
            hb = hb + lr * jnp.sum((h0p - hkp) * mask, axis=0) / n
            err = jnp.sum(((v0 - vk) * mask) ** 2) / n
            return w, vb, hb, err

        from veles_tpu.telemetry import track_jit
        return track_jit("rbm.step",
                         jax.jit(step, donate_argnums=(0, 1, 2)))

    def run(self):
        if self._step_ is None:
            self._step_ = self._build_step()
        l = self.loader
        key = self.prng.peek_key(self.global_step)
        w, vb, hb, err = self._step_(
            self.weights.donatable_devmem(),
            self.vbias.donatable_devmem(),
            self.hbias.donatable_devmem(),
            l.minibatch_data.devmem, jnp.int32(l.minibatch_size), key)
        self.weights.devmem = w
        self.vbias.devmem = vb
        self.hbias.devmem = hb
        self.recon_error.devmem = err
        self.global_step += 1

    def step(self, **tensors):
        raise RuntimeError("BernoulliRBM dispatches its own program")
