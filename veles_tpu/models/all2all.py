"""Fully-connected layers (reconstruction of znicz all2all, surface per
manualrst_veles_algorithms.rst "Fully-connected Neural Networks"; the
GEMM rides the MXU through :func:`veles_tpu.ops.gemm.matmul`)."""

import jax.numpy as jnp
import numpy

from veles_tpu.memory import Array
from veles_tpu.models.activations import get_activation
from veles_tpu.models.nn_units import ForwardBase
from veles_tpu.ops.gemm import matmul


class All2All(ForwardBase):
    """y = activation(x @ W + b) with x flattened to [batch, features]
    (znicz All2All; weights stored [in, out] so the forward GEMM is
    layout-natural for the MXU)."""

    ACTIVATION = "linear"

    def __init__(self, workflow, output_sample_shape=None,
                 output_samples_number=None, activation=None, **kwargs):
        super(All2All, self).__init__(workflow, **kwargs)
        if output_sample_shape is None and output_samples_number is None:
            raise ValueError("output_sample_shape is required")
        self.output_sample_shape = tuple(
            numpy.atleast_1d(output_sample_shape
                             or output_samples_number).tolist())
        self.activation = activation or self.ACTIVATION

    @property
    def neurons_number(self):
        return int(numpy.prod(self.output_sample_shape))

    def output_shape_for(self, input_shape):
        return (input_shape[0],) + self.output_sample_shape

    def fill_params(self):
        fan_in = int(numpy.prod(self.input.shape[1:]))
        fan_out = self.neurons_number
        self.weights.reset(numpy.zeros((fan_in, fan_out), numpy.float32))
        self._fill(self.weights.mem, self.weights_filling,
                   self.weights_stddev, fan_in, fan_out)
        if self.include_bias:
            self.bias.reset(numpy.zeros((fan_out,), numpy.float32))
            self._fill(self.bias.mem, self.bias_filling,
                       self.bias_stddev or 0.0, fan_in, fan_out)

    def apply(self, params, x):
        # activations stay in the compute dtype (bf16) through the FC
        # trunk — the 4096-wide AlexNet layers are HBM-bandwidth-bound
        # like the convs, and the MXU still accumulates in f32 inside
        # the matmul; the evaluator recasts to f32 for the loss
        from veles_tpu import dtypes
        y = matmul(x.reshape(x.shape[0], -1), params["weights"],
                   out_dtype=dtypes.compute_dtype())
        if self.include_bias:
            y = y + params["bias"].astype(y.dtype)
        y = get_activation(self.activation)(y)
        return y.reshape((x.shape[0],) + self.output_sample_shape)

    def export_config(self):
        return {"output_sample_shape": list(self.output_sample_shape),
                "activation": self._export_activation(),
                "include_bias": self.include_bias}


class All2AllTanh(All2All):
    ACTIVATION = "tanh"


class All2AllRELU(All2All):
    ACTIVATION = "relu"


class All2AllStrictRELU(All2All):
    ACTIVATION = "strict_relu"


class All2AllSigmoid(All2All):
    ACTIVATION = "sigmoid"


class All2AllSoftmax(All2All):
    """FC + softmax head (znicz All2AllSoftmax): ``output`` holds the
    probabilities, ``max_idx`` the argmax per sample, ``logits_out``
    the pre-softmax scores (evaluators compute the CE loss from these —
    reconstructing logits as log(probs) loses precision)."""

    ACTIVATION = "linear"
    WRITES = ("output", "max_idx", "logits_out")

    def __init__(self, workflow, **kwargs):
        super(All2AllSoftmax, self).__init__(workflow, **kwargs)
        self.max_idx = Array()
        self.logits_out = Array()

    def initialize(self, device=None, **kwargs):
        super(All2AllSoftmax, self).initialize(device=device, **kwargs)
        self.max_idx.reset(numpy.zeros((self.input.shape[0],),
                                       numpy.int32))
        self.logits_out.reset(numpy.zeros(self.output.shape,
                                          numpy.float32))

    def logits(self, params, x):
        """Pre-softmax scores — the trainer's softmax-CE loss composes
        over these for numerical stability, so unlike the hidden FC
        layers (bf16 activations) the head keeps the matmul's f32
        accumulator output."""
        z = matmul(x.reshape(x.shape[0], -1), params["weights"])
        if self.include_bias:
            z = z + params["bias"]
        # identity for the default "linear" head; kept for heads
        # constructed with an explicit activation kwarg
        z = get_activation(self.activation)(z)
        return z.reshape((x.shape[0],) + self.output_sample_shape)

    def apply(self, params, x):
        z = self.logits(params, x)
        probs = jnp.exp(z - jnp.max(z, axis=-1, keepdims=True))
        return probs / jnp.sum(probs, axis=-1, keepdims=True)

    def step(self, input, **params):
        z = self.logits(params, input)
        probs = jnp.exp(z - jnp.max(z, axis=-1, keepdims=True))
        probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
        return {"output": probs,
                "max_idx": jnp.argmax(probs, axis=-1).astype(jnp.int32),
                "logits_out": z.astype(jnp.float32)}
