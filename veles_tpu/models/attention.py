"""Multi-head attention forward unit — the sequence-model entry of the
zoo (no reference analogue: RNN/LSTM existed only untested in the
absent Znicz submodule, manualrst_veles_algorithms.rst:115-140).

This unit's ``apply`` is the single-program formulation (XLA/GSPMD
shards it like any other op).  For long contexts where each chip must
hold only 1/sp of K/V, the trainer hands the unit its mesh
(``sp_mesh_``) and the attention core switches to the RING schedule
under ``shard_map`` — sequence-sharded training end-to-end, gradients
flowing through the ppermute ring (ops/attention.py); GSPMD cannot
derive that communication schedule from the single-program form."""

import functools

import numpy

from veles_tpu.models.nn_units import ForwardBase




def _ring_mha(mesh, q, k, v, causal):
    """The sp-sharded attention core: q/k/v [batch, seq, heads, hd]
    with seq over ``sp`` (and batch over dp/fsdp when present); K/V
    rotate around the ring so each chip only ever holds seq/sp of
    them."""
    try:
        from jax import shard_map
    except ImportError:  # jax < 0.5 keeps it in experimental
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from veles_tpu.ops.attention import ring_attention
    batch_axes = tuple(a for a in ("dp", "fsdp")
                       if mesh.shape.get(a, 1) > 1) or None
    spec = P(batch_axes, "sp", None, None)
    fn = shard_map(
        functools.partial(ring_attention, axis_name="sp",
                          causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)


def mha_apply(params, x, heads, causal, block_size=None, sp_mesh=None,
              attn_impl=None, backend=None):
    """Multi-head attention forward over [batch, seq, d] — the ONE
    implementation shared by the MultiHeadAttention unit and
    TransformerBlock (params: wq/wk/wv/wo, each [d, d]).  Projections
    run in the compute dtype (bf16 trunk policy); the attention core
    is selected in priority order:

    - ``sp_mesh`` with an sp axis > 1 → the ppermute RING (sequence
      parallelism is a communication schedule, it overrides the rest);
    - ``attn_impl`` "flash" | "blockwise" | "dense" → that core;
    - default (None/"auto") → the framework's NATIVE pallas flash
      kernels on TPU at any sequence length (lane-multiple head_dim;
      ops/pallas_attention.py), else blockwise streaming if
      ``block_size`` says so, else the plain single-program form."""
    import jax.numpy as jnp

    from veles_tpu import dtypes
    from veles_tpu.ops.attention import attention
    cd = dtypes.compute_dtype()
    ad = dtypes.accum_dtype()
    prec = dtypes.matmul_precision()
    b, s, d = x.shape
    hd = d // heads

    def proj(w):
        y = jnp.einsum("bsd,de->bse", x.astype(cd), w.astype(cd),
                       precision=prec, preferred_element_type=ad)
        return y.astype(cd).reshape(b, s, heads, hd)

    sp = sp_mesh.shape.get("sp", 1) if sp_mesh is not None else 0
    if sp > 1:
        o = _ring_mha(sp_mesh, proj(params["wq"]), proj(params["wk"]),
                      proj(params["wv"]), causal)
    else:
        impl = attn_impl or "auto"
        if impl == "auto":
            from veles_tpu.ops.common import resolve_backend, \
                ACCEL_PLATFORMS
            # the NATIVE kernels are the default at EVERY length (r5:
            # clamped causal index maps skip dead-block DMAs and
            # 1024-token K blocks fix the long-context bookkeeping —
            # measured past the jax-shipped kernel at 2048, 8192 AND
            # 32768; ROUND5_NOTES.md §5).  Odd lengths pad-and-mask
            # inside the kernel.  head_dim off the lane width falls
            # back (the MXU would run mostly idle); attn_impl pins
            # either kernel explicitly.
            if resolve_backend(backend) in ACCEL_PLATFORMS \
                    and hd % 128 == 0:
                impl = "pallas"
            else:
                impl = "blockwise" if block_size else "dense"
        q, k, v = (proj(params[n]) for n in ("wq", "wk", "wv"))
        if impl == "flash":
            from veles_tpu.ops.flash import flash_attention
            o = flash_attention(q, k, v, causal=causal,
                                backend=backend)
        elif impl == "pallas":
            # the framework's OWN flash kernels (ops/pallas_attention)
            from veles_tpu.ops.pallas_attention import pallas_attention
            o = pallas_attention(q, k, v, causal=causal,
                                 backend=backend)
        elif impl == "blockwise":
            from veles_tpu.ops.attention import blockwise_attention
            o = blockwise_attention(q, k, v, block_size or 512,
                                    causal=causal)
        elif impl == "dense":
            o = attention(q, k, v, causal=causal)
        else:
            raise ValueError("unknown attn_impl %r" % (attn_impl,))
    return jnp.einsum("bsd,de->bse", o.reshape(b, s, d).astype(cd),
                      params["wo"].astype(cd),
                      precision=prec,
                      preferred_element_type=ad).astype(x.dtype)


class MultiHeadAttention(ForwardBase):
    """y = (softmax(QK^T/sqrt(d)) V) Wo with Q/K/V = x·Wq/Wk/Wv.

    x: [batch, seq, model_dim]."""

    #: minibatch dim 1 is a SEQUENCE dim for this unit — the
    #: trainer sp-shards data dim 1 only when a forward says so
    #: (ADVICE.md r4 #2: sp sharding is opt-in)
    SEQ_DIM1_INPUT = True

    PARAMS = ("wq", "wk", "wv", "wo")

    def __init__(self, workflow, heads=4, causal=False,
                 block_size=None, attn_impl=None, **kwargs):
        from veles_tpu.memory import Array
        super(MultiHeadAttention, self).__init__(workflow, **kwargs)
        self.heads = int(heads)
        self.causal = causal
        #: stream K/V in blocks of this many tokens (long sequences:
        #: avoids the [seq, seq] score matrix; ops/attention.py)
        self.block_size = block_size
        #: attention core override: "flash" | "blockwise" | "dense"
        #: (None = auto; see mha_apply)
        self.attn_impl = attn_impl
        for p in self.PARAMS:
            setattr(self, p, Array())

    def output_shape_for(self, input_shape):
        return tuple(input_shape)

    def fill_params(self):
        d = self.input.shape[-1]
        if d % self.heads:
            raise ValueError("model dim %d not divisible by %d heads"
                             % (d, self.heads))
        for p in self.PARAMS:
            arr = getattr(self, p)
            arr.reset(numpy.zeros((d, d), numpy.float32))
            self._fill(arr.mem, self.weights_filling,
                       self.weights_stddev, d, d)

    def export_config(self):
        cfg = {"heads": self.heads, "causal": self.causal}
        if self.block_size:  # v2 key — omit when unused so plain
            cfg["block_size"] = int(self.block_size)  # packages stay v1
        if self.attn_impl:  # an explicit core pin must survive export
            cfg["attn_impl"] = self.attn_impl
        return cfg

    def apply(self, params, x):
        dev = getattr(self, "device", None)
        return mha_apply(params, x, self.heads, self.causal,
                         self.block_size,
                         sp_mesh=getattr(self, "sp_mesh_", None),
                         attn_impl=getattr(self, "attn_impl", None),
                         backend=dev.jax_device.platform if dev else None)
