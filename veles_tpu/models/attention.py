"""Multi-head attention forward unit — the sequence-model entry of the
zoo (no reference analogue: RNN/LSTM existed only untested in the
absent Znicz submodule, manualrst_veles_algorithms.rst:115-140).

This unit's ``apply`` is the single-program formulation (XLA/GSPMD
shards it like any other op).  For long contexts where each chip must
hold only 1/sp of K/V, use veles_tpu.ops.attention.ring_attention_
sharded explicitly — the ring is a different communication schedule,
not something sharding propagation derives from this op."""

import numpy

from veles_tpu.models.nn_units import ForwardBase


def mha_apply(params, x, heads, causal, block_size=None):
    """Multi-head attention forward over [batch, seq, d] — the ONE
    implementation shared by the MultiHeadAttention unit and
    TransformerBlock (params: wq/wk/wv/wo, each [d, d]).  Projections
    run in the compute dtype (bf16 trunk policy); the attention core
    is ops.attention."""
    import jax.numpy as jnp

    from veles_tpu import dtypes
    from veles_tpu.ops.attention import attention
    cd = dtypes.compute_dtype()
    ad = dtypes.accum_dtype()
    prec = dtypes.matmul_precision()
    b, s, d = x.shape
    hd = d // heads

    def proj(w):
        y = jnp.einsum("bsd,de->bse", x.astype(cd), w.astype(cd),
                       precision=prec, preferred_element_type=ad)
        return y.astype(cd).reshape(b, s, heads, hd)

    if block_size:
        from veles_tpu.ops.attention import blockwise_attention
        o = blockwise_attention(proj(params["wq"]), proj(params["wk"]),
                                proj(params["wv"]), block_size,
                                causal=causal)
    else:
        o = attention(proj(params["wq"]), proj(params["wk"]),
                      proj(params["wv"]), causal=causal)
    return jnp.einsum("bsd,de->bse", o.reshape(b, s, d).astype(cd),
                      params["wo"].astype(cd),
                      precision=prec,
                      preferred_element_type=ad).astype(x.dtype)


class MultiHeadAttention(ForwardBase):
    """y = (softmax(QK^T/sqrt(d)) V) Wo with Q/K/V = x·Wq/Wk/Wv.

    x: [batch, seq, model_dim]."""

    PARAMS = ("wq", "wk", "wv", "wo")

    def __init__(self, workflow, heads=4, causal=False,
                 block_size=None, **kwargs):
        from veles_tpu.memory import Array
        super(MultiHeadAttention, self).__init__(workflow, **kwargs)
        self.heads = int(heads)
        self.causal = causal
        #: stream K/V in blocks of this many tokens (long sequences:
        #: avoids the [seq, seq] score matrix; ops/attention.py)
        self.block_size = block_size
        for p in self.PARAMS:
            setattr(self, p, Array())

    def output_shape_for(self, input_shape):
        return tuple(input_shape)

    def fill_params(self):
        d = self.input.shape[-1]
        if d % self.heads:
            raise ValueError("model dim %d not divisible by %d heads"
                             % (d, self.heads))
        for p in self.PARAMS:
            arr = getattr(self, p)
            arr.reset(numpy.zeros((d, d), numpy.float32))
            self._fill(arr.mem, self.weights_filling,
                       self.weights_stddev, d, d)

    def export_config(self):
        cfg = {"heads": self.heads, "causal": self.causal}
        if self.block_size:  # v2 key — omit when unused so plain
            cfg["block_size"] = int(self.block_size)  # packages stay v1
        return cfg

    def apply(self, params, x):
        return mha_apply(params, x, self.heads, self.causal,
                         self.block_size)
