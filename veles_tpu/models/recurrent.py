"""Recurrent layers — SimpleRNN and LSTM forward units
(manualrst_veles_algorithms.rst "Recurrent Neural Networks" / "Long
short-term memory": the reference's units existed in the absent Znicz
submodule with status "created but not tested"; these are live and
tested).

x: [batch, time, features] → outputs [batch, time, hidden]; the time
loop is ``lax.scan`` (static-shape, TPU-compilable), hidden state
carried functionally.
"""

import jax
import jax.numpy as jnp
import numpy

from veles_tpu.models.nn_units import ForwardBase
from veles_tpu.ops.gemm import matmul


class SimpleRNN(ForwardBase):
    """h_t = tanh(x_t·Wx + h_{t-1}·Wh + b)."""

    #: minibatch dim 1 is a SEQUENCE dim for this unit — the
    #: trainer sp-shards data dim 1 only when a forward says so
    #: (ADVICE.md r4 #2: sp sharding is opt-in)
    SEQ_DIM1_INPUT = True

    PARAMS = ("wx", "wh", "bias")

    def __init__(self, workflow, hidden=None, **kwargs):
        from veles_tpu.memory import Array
        super(SimpleRNN, self).__init__(workflow, **kwargs)
        if hidden is None:
            raise ValueError("hidden is required")
        self.hidden = int(hidden)
        for p in self.PARAMS:
            setattr(self, p, Array())

    def output_shape_for(self, input_shape):
        return (input_shape[0], input_shape[1], self.hidden)

    def fill_params(self):
        f = self.input.shape[-1]
        h = self.hidden
        self.wx.reset(numpy.zeros((f, h), numpy.float32))
        self._fill(self.wx.mem, self.weights_filling,
                   self.weights_stddev, f, h)
        self.wh.reset(numpy.zeros((h, h), numpy.float32))
        self._fill(self.wh.mem, self.weights_filling,
                   self.weights_stddev, h, h)
        self.bias.reset(numpy.zeros((h,), numpy.float32))

    def apply(self, params, x):
        def cell(h, xt):
            h = jnp.tanh(matmul(xt, params["wx"], out_dtype=xt.dtype)
                         + matmul(h, params["wh"], out_dtype=xt.dtype)
                         + params["bias"])
            return h, h

        h0 = jnp.zeros((x.shape[0], self.hidden), x.dtype)
        _, ys = jax.lax.scan(cell, h0, jnp.swapaxes(x, 0, 1))
        return jnp.swapaxes(ys, 0, 1)


class LSTM(ForwardBase):
    """Standard LSTM (i, f, g, o gates; one fused [f+h, 4h] GEMM per
    step rides the MXU)."""

    #: minibatch dim 1 is a SEQUENCE dim for this unit — the
    #: trainer sp-shards data dim 1 only when a forward says so
    #: (ADVICE.md r4 #2: sp sharding is opt-in)
    SEQ_DIM1_INPUT = True

    PARAMS = ("weights", "bias")

    def __init__(self, workflow, hidden=None, forget_bias=1.0, **kwargs):
        super(LSTM, self).__init__(workflow, **kwargs)
        if hidden is None:
            raise ValueError("hidden is required")
        self.hidden = int(hidden)
        self.forget_bias = float(forget_bias)

    def output_shape_for(self, input_shape):
        return (input_shape[0], input_shape[1], self.hidden)

    def fill_params(self):
        f = self.input.shape[-1]
        h = self.hidden
        self.weights.reset(numpy.zeros((f + h, 4 * h), numpy.float32))
        self._fill(self.weights.mem, self.weights_filling,
                   self.weights_stddev, f + h, 4 * h)
        self.bias.reset(numpy.zeros((4 * h,), numpy.float32))

    def apply(self, params, x):
        h_dim = self.hidden

        def cell(carry, xt):
            h, c = carry
            z = matmul(jnp.concatenate([xt, h], axis=1),
                       params["weights"], out_dtype=xt.dtype) \
                + params["bias"]
            i, f, g, o = jnp.split(z, 4, axis=1)
            c = jax.nn.sigmoid(f + self.forget_bias) * c \
                + jax.nn.sigmoid(i) * jnp.tanh(g)
            h = jax.nn.sigmoid(o) * jnp.tanh(c)
            return (h, c), h

        zeros = jnp.zeros((x.shape[0], h_dim), x.dtype)
        _, ys = jax.lax.scan(cell, (zeros, zeros),
                             jnp.swapaxes(x, 0, 1))
        return jnp.swapaxes(ys, 0, 1)


class LastTimestep(ForwardBase):
    """[batch, time, h] → [batch, h] (sequence classifier heads read
    the final state)."""

    PARAMS = ()

    def fill_params(self):
        pass

    def output_shape_for(self, input_shape):
        return (input_shape[0], input_shape[2])

    def apply(self, params, x):
        return x[:, -1, :]
