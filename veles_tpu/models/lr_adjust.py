"""Learning-rate schedules (reconstruction of znicz lr_adjust; extras
item 3 "Learning rate adjusting").

A policy maps the global step (or epoch) to a multiplier on the base
learning rate.  Policies are pure — the trainer traces them, so schedule
evaluation is free inside the fused step.
"""

import jax.numpy as jnp


class ConstantLR:
    def __init__(self, **kwargs):
        pass

    def __call__(self, step):
        return 1.0


class StepLR:
    """lr *= gamma every ``step_size`` steps (caffe 'step')."""

    def __init__(self, gamma=0.1, step_size=100000, **kwargs):
        self.gamma = gamma
        self.step_size = step_size

    def __call__(self, step):
        return self.gamma ** jnp.floor(step / self.step_size)


class ExpLR:
    """lr *= gamma^step (caffe 'exp')."""

    def __init__(self, gamma=0.9999, **kwargs):
        self.gamma = gamma

    def __call__(self, step):
        return self.gamma ** step


class InvLR:
    """lr / (1 + gamma*step)^power (caffe 'inv')."""

    def __init__(self, gamma=0.0001, power=0.75, **kwargs):
        self.gamma = gamma
        self.power = power

    def __call__(self, step):
        return (1.0 + self.gamma * step) ** (-self.power)


class CosineLR:
    """Half-cosine decay from 1 to ``floor`` over ``total_steps``, with
    an optional linear warmup (the standard modern training recipe;
    no caffe analogue — the reference predates it)."""

    def __init__(self, total_steps=100000, floor=0.0, warmup=0,
                 **kwargs):
        self.total_steps = total_steps
        self.floor = floor
        self.warmup = warmup

    def __call__(self, step):
        # warmup-THEN-cosine (ADVICE.md r4 #4): the linear ramp runs to
        # the full peak multiplier, and the cosine phase starts at the
        # end of warmup — not a ramp multiplied onto an already-decaying
        # cosine, which never reaches 1.0
        denom = max(self.total_steps - self.warmup, 1)
        frac = jnp.clip((step - self.warmup) / denom, 0.0, 1.0)
        mult = self.floor + (1.0 - self.floor) * 0.5 * (
            1.0 + jnp.cos(jnp.pi * frac))
        if self.warmup:
            mult = jnp.where(step < self.warmup,
                             step / self.warmup, mult)
        return mult


SCHEDULES = {"constant": ConstantLR, "step": StepLR, "exp": ExpLR,
             "inv": InvLR, "cosine": CosineLR}


def get_schedule(name, **kwargs):
    if callable(name) and not isinstance(name, str):
        return name
    return SCHEDULES[name](**kwargs)
