"""Token embedding — the sequence-model input unit (no reference
analogue: sequence models existed only as untested Znicz units,
manualrst_veles_algorithms.rst:115-140; the TPU rebuild makes the
sequence stack first-class per the driver's long-context mandate).
"""

import jax
import jax.numpy as jnp
import numpy

from veles_tpu.models.nn_units import ForwardBase


class Embedding(ForwardBase):
    """[batch, seq] int tokens -> [batch, seq, dim] vectors.

    The gather rides HBM (``jnp.take``); the table is a plain
    parameter so tp/fsdp sharding conventions apply to it like any
    weight matrix."""

    #: minibatch dim 1 is a SEQUENCE dim for this unit — the
    #: trainer sp-shards data dim 1 only when a forward says so
    #: (ADVICE.md r4 #2: sp sharding is opt-in)
    SEQ_DIM1_INPUT = True

    PARAMS = ("weights", "positions")

    def __init__(self, workflow, vocab=None, dim=None,
                 learned_positions=True, **kwargs):
        from veles_tpu.memory import Array
        super(Embedding, self).__init__(workflow, include_bias=False,
                                        **kwargs)
        if not vocab or not dim:
            raise ValueError("vocab and dim are required")
        self.vocab = int(vocab)
        self.dim = int(dim)
        #: add a learned positional table (sequence tasks are almost
        #: always position-relative; attention alone is permutation-
        #: equivariant without it)
        self.learned_positions = bool(learned_positions)
        self.positions = Array()

    def output_shape_for(self, input_shape):
        return tuple(input_shape) + (self.dim,)

    def fill_params(self):
        self.weights.reset(numpy.zeros((self.vocab, self.dim),
                                       numpy.float32))
        self._fill(self.weights.mem, self.weights_filling,
                   self.weights_stddev or 0.02, self.vocab, self.dim)
        if self.learned_positions:
            seq = int(self.input.shape[1])
            self.positions.reset(numpy.zeros((seq, self.dim),
                                             numpy.float32))
            self._fill(self.positions.mem, self.weights_filling,
                       self.weights_stddev or 0.02, seq, self.dim)

    def param_arrays(self):
        arrs = super(Embedding, self).param_arrays()
        if not self.learned_positions:
            arrs.pop("positions", None)
        return arrs

    def apply(self, params, x):
        from veles_tpu import dtypes
        cd = dtypes.compute_dtype()
        y = jnp.take(params["weights"].astype(cd),
                     x.astype(jnp.int32), axis=0)
        if self.learned_positions:
            y = y + params["positions"].astype(cd)[
                None, :y.shape[1], :]
        return y

    def apply_step(self, params, x, pos):
        """Single-position decode (models/generate.py kv_cache path):
        x [batch, 1] token ids at sequence index ``pos`` (traced
        scalar) — the positional row is gathered dynamically."""
        from veles_tpu import dtypes
        cd = dtypes.compute_dtype()
        y = jnp.take(params["weights"].astype(cd),
                     x.astype(jnp.int32), axis=0)
        if self.learned_positions:
            row = jax.lax.dynamic_slice(
                params["positions"].astype(cd), (pos, 0),
                (1, self.dim))
            y = y + row[None]
        return y

    def apply_chunk(self, params, x, offset):
        """Chunked-prefill lookup: x [batch, C] token ids occupying
        sequence positions [offset, offset+C) (``offset`` traced).
        The positional rows are gathered per index with clamping, so a
        tail chunk whose padding overruns the learned table reads a
        (masked-off) clamped row instead of shifting valid rows the
        way a clamped dynamic_slice would."""
        from veles_tpu import dtypes
        cd = dtypes.compute_dtype()
        y = jnp.take(params["weights"].astype(cd),
                     x.astype(jnp.int32), axis=0)
        if self.learned_positions:
            rows = jnp.take(params["positions"].astype(cd),
                            offset + jnp.arange(x.shape[1]), axis=0)
            y = y + rows[None]
        return y

    def apply_step_slots(self, params, x, pos):
        """Per-slot decode step (serving path): x [batch, 1] token
        ids where row n sits at ITS OWN sequence index ``pos[n]``
        ([batch] ints, traced) — each slot's positional row is
        gathered independently."""
        from veles_tpu import dtypes
        cd = dtypes.compute_dtype()
        y = jnp.take(params["weights"].astype(cd),
                     x.astype(jnp.int32), axis=0)
        if self.learned_positions:
            rows = jnp.take(params["positions"].astype(cd),
                            pos, axis=0)
            y = y + rows[:, None, :]
        return y

    def apply_verify_slots(self, params, x, pos):
        """Speculative-verify lookup: x [batch, K1] token ids where
        row n's position j sits at sequence index ``pos[n] + j``
        ([batch] ints, traced).  Positional rows are gathered per
        index with clamping — bucket-padding positions past the
        learned table read a (masked-off) clamped row, matching
        :meth:`apply_chunk`'s convention."""
        from veles_tpu import dtypes
        cd = dtypes.compute_dtype()
        y = jnp.take(params["weights"].astype(cd),
                     x.astype(jnp.int32), axis=0)
        if self.learned_positions:
            idx = jnp.clip(
                pos[:, None] + jnp.arange(x.shape[1])[None, :], 0,
                params["positions"].shape[0] - 1)
            y = y + jnp.take(params["positions"].astype(cd), idx,
                             axis=0)
        return y

    def export_config(self):
        return {"vocab": self.vocab, "dim": self.dim,
                "learned_positions": self.learned_positions}
