"""Mixture-of-Experts FFN — the layer behind the ``ep`` mesh axis.

The reference has no MoE (SURVEY §2.3: every parallel strategy beyond
elastic DP is absent there); this unit exists so expert parallelism is
a first-class strategy like sp/pp, per the SURVEY "TPU mapping"
mandate.  Design:

- top-k gating: softmax over the k largest gate logits per sample,
  re-normalized (standard switch/top-2 routing without capacity
  limits);
- **dense einsum dispatch**: every expert sees every token and the
  combine weights zero out non-selected experts.  At framework scale
  this trades FLOPs for zero all-to-all machinery — and it makes the
  ``ep`` sharding story pure XLA: expert-major parameters are sharded
  over ``ep`` (see ``parallel/sharding.py``), the expert einsums run
  expert-local, and the final combine contracts the expert dimension,
  which XLA lowers to a ``psum`` over ``ep`` on ICI.

Trains through :class:`~veles_tpu.models.gd.GradientDescent` like any
ForwardBase chain (the gate and experts get gradients from the task
loss; no auxiliary load-balancing loss — dense dispatch has no
capacity overflow to balance against).
"""

import jax
import jax.numpy as jnp
import numpy

from veles_tpu.memory import Array
from veles_tpu.models.activations import get_activation
from veles_tpu.models.nn_units import ForwardBase


def moe_apply(params, x, top_k, activation):
    """The MoE forward over the LAST axis of ``x`` (any rank: leading
    dims are all batch-like).  Shared by the MoE unit and the
    TransformerBlock's expert FFN; ``params`` carries ``gate`` [d, E]
    and the expert-major ``expert_*`` tensors."""
    from veles_tpu import dtypes
    cd = dtypes.compute_dtype() if jnp.issubdtype(
        x.dtype, jnp.floating) else x.dtype
    d = x.shape[-1]
    n_experts = params["expert_w1"].shape[0]
    xf = x.reshape(-1, d).astype(cd)
    # top-k gating: softmax over the k largest logits, zero elsewhere
    logits = xf @ params["gate"].astype(xf.dtype)
    vals, idx = jax.lax.top_k(logits, top_k)
    probs = jax.nn.softmax(vals, axis=-1)
    onehot = jax.nn.one_hot(idx, n_experts, dtype=xf.dtype)
    c = jnp.einsum("bk,bke->be", probs.astype(xf.dtype), onehot)
    act = get_activation(activation)
    # dense dispatch: expert dim e is batch-like in the einsums, so
    # ep-sharded expert params keep both matmuls expert-local...
    h1 = jnp.einsum("bd,edh->ebh", xf, params["expert_w1"].astype(cd),
                    preferred_element_type=jnp.float32)
    h1 = act((h1 + params["expert_b1"].astype(
        jnp.float32)[:, None, :]).astype(cd))
    y = jnp.einsum("ebh,ehd->ebd", h1, params["expert_w2"].astype(cd),
                   preferred_element_type=jnp.float32)
    y = y + params["expert_b2"].astype(jnp.float32)[:, None, :]
    # ...and the combine contracts e — the one collective (psum over
    # ep) of the whole layer
    out = jnp.einsum("be,ebd->bd", c.astype(jnp.float32), y)
    return out.astype(x.dtype).reshape(x.shape)


class MoE(ForwardBase):
    """Top-k gated mixture of expert FFNs over the last feature axis.

    x: [batch, d] -> y: [batch, d]; experts are 2-layer FFNs
    d -> hidden -> d.  Expert-major params (``expert_*``) shard over
    the ``ep`` mesh axis.
    """

    PARAMS = ("gate", "expert_w1", "expert_b1", "expert_w2",
              "expert_b2")
    ACTIVATION = "strict_relu"  # true max(0,x) — znicz "relu" is softplus

    def __init__(self, workflow, n_experts=4, top_k=2, hidden=None,
                 activation=None, **kwargs):
        super(MoE, self).__init__(workflow, **kwargs)
        self.n_experts = int(n_experts)
        self.top_k = int(top_k)
        if self.top_k > self.n_experts:
            raise ValueError("top_k %d > n_experts %d"
                             % (self.top_k, self.n_experts))
        self.hidden = hidden  # None -> 4*d at fill time
        self.activation = activation or self.ACTIVATION
        self.gate = Array()
        self.expert_w1 = Array()
        self.expert_b1 = Array()
        self.expert_w2 = Array()
        self.expert_b2 = Array()

    def output_shape_for(self, input_shape):
        return input_shape

    def fill_params(self):
        # last-dim semantics: leading dims (batch, sequence, …) are all
        # batch-like, matching moe_apply
        d = int(self.input.shape[-1])
        h = int(self.hidden or 4 * d)
        self.hidden = h
        e = self.n_experts
        self.gate.reset(numpy.zeros((d, e), numpy.float32))
        self._fill(self.gate.mem, self.weights_filling,
                   self.weights_stddev, d, e)
        self.expert_w1.reset(numpy.zeros((e, d, h), numpy.float32))
        self.expert_w2.reset(numpy.zeros((e, h, d), numpy.float32))
        for w, fi, fo in ((self.expert_w1.mem, d, h),
                          (self.expert_w2.mem, h, d)):
            for i in range(e):
                self._fill(w[i], self.weights_filling,
                           self.weights_stddev, fi, fo)
        self.expert_b1.reset(numpy.zeros((e, h), numpy.float32))
        self.expert_b2.reset(numpy.zeros(
            (e, d), numpy.float32))

    def apply(self, params, x):
        return moe_apply(params, x, self.top_k, self.activation)

    def export_config(self):
        return {"n_experts": self.n_experts, "top_k": self.top_k,
                "hidden": int(self.hidden),
                "activation": self._export_activation()}
