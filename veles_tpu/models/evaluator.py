"""Evaluators — loss + error metrics (reconstruction of znicz
evaluator.EvaluatorSoftmax / EvaluatorMSE; loss surface per
manualrst_veles_algorithms.rst "Loss functions: mse, softmax").

Each evaluator plays two roles:

- a pure ``loss(y, target, size)`` the trainer traces into its fused
  autodiff program (``y`` is logits for softmax, raw output for MSE);
  padded tail rows are masked by ``size``;
- an in-graph unit computing per-minibatch metrics (n_err / confusion
  for softmax, mse per sample for MSE) from the forward chain's output.

The unit is not fused: it reads the loader's host-side ``minibatch_size``
each run (FUSABLE=False keeps the refresh ordered before execution).
"""

import jax.numpy as jnp
import numpy

from veles_tpu.accelerated_units import AcceleratedUnit
from veles_tpu.memory import Array
from veles_tpu.units import MissingDemand


def masked_ce_from_logits(logits, labels, size, per_row_positions=1):
    """Masked mean softmax cross-entropy, shared by the classifier and
    sequence evaluators: ``logits`` [rows, ..., V] (f32-cast here),
    ``labels`` [rows, ...] int, rows >= ``size`` masked away; the mean
    divides by size · per_row_positions (1 for classifiers, seq-1 for
    next-token)."""
    logits = logits.astype(jnp.float32)
    z = logits - jnp.max(logits, axis=-1, keepdims=True)
    logp = z - jnp.log(jnp.sum(jnp.exp(z), axis=-1, keepdims=True))
    picked = jnp.take_along_axis(
        logp, jnp.clip(labels, 0)[..., None].astype(jnp.int32),
        axis=-1)[..., 0]
    mask = jnp.arange(logits.shape[0]) < size
    mask = mask.reshape((-1,) + (1,) * (picked.ndim - 1))
    return -jnp.sum(jnp.where(mask, picked, 0.0)) \
        / jnp.maximum(size, 1) / per_row_positions


class EvaluatorBase(AcceleratedUnit):
    hide_from_registry = True
    VIEW_GROUP = "EVALUATOR"
    FUSABLE = False

    def __init__(self, workflow, **kwargs):
        super(EvaluatorBase, self).__init__(workflow, **kwargs)
        self.output = None       # linked from the head forward unit
        self.batch_size = Array()
        self.loader = None       # linked for minibatch_size refresh
        self.demand("output")

    def initialize(self, device=None, **kwargs):
        if not isinstance(self.output, Array) or not bool(self.output):
            raise MissingDemand(self, {"output"})
        self.batch_size.reset(numpy.zeros((), numpy.int32))
        super(EvaluatorBase, self).initialize(device=device, **kwargs)

    def run(self):
        if self.loader is not None:
            self.batch_size.map_invalidate()
            self.batch_size.mem[...] = self.loader.minibatch_size
            self.batch_size.unmap()
        super(EvaluatorBase, self).run()


class EvaluatorSoftmax(EvaluatorBase):
    """Cross-entropy over softmax probabilities; metrics: ``n_err``
    (miscount in the minibatch) and the ``confusion_matrix``
    (znicz EvaluatorSoftmax surface)."""

    WRITES = ("n_err", "loss_out")

    def __init__(self, workflow, compute_confusion_matrix=True, **kwargs):
        super(EvaluatorSoftmax, self).__init__(workflow, **kwargs)
        self.labels = None       # linked from loader.minibatch_labels
        self.max_idx = None      # linked from All2AllSoftmax (optional)
        #: link All2AllSoftmax.logits_out here for an exact in-graph
        #: loss; without it the loss falls back to log(probs) (lossy
        #: near-saturated softmax — VERDICT r1 weak #7)
        self.logits = None
        self.n_err = Array()
        self.loss_out = Array()
        self.compute_confusion_matrix = compute_confusion_matrix
        self.confusion_matrix = Array()
        self.demand("labels")

    @property
    def reads(self):
        base = ("output", "labels", "batch_size")
        return base + (("logits",) if isinstance(self.logits, Array)
                       else ())

    @property
    def writes(self):
        return ("n_err", "loss_out") + (
            ("confusion_matrix",) if self.compute_confusion_matrix else ())

    def initialize(self, device=None, **kwargs):
        super(EvaluatorSoftmax, self).initialize(device=device, **kwargs)
        self.n_err.reset(numpy.zeros((), numpy.int32))
        self.loss_out.reset(numpy.zeros((), numpy.float32))
        n_classes = self.output.shape[-1]
        if self.compute_confusion_matrix:
            self.confusion_matrix.reset(
                numpy.zeros((n_classes, n_classes), numpy.int32))

    # -- trainer-facing loss ---------------------------------------------------

    @staticmethod
    def loss_from_logits(logits, labels, size):
        """Masked mean softmax cross-entropy over valid rows (always in
        f32 — the forward chain may run bf16 activations)."""
        return masked_ce_from_logits(logits, labels, size)

    def loss(self, y, labels, size):
        return self.loss_from_logits(y, labels, size)

    # -- in-graph metrics ------------------------------------------------------

    def step(self, output, labels, batch_size, logits=None):
        pred = jnp.argmax(output, axis=-1).astype(jnp.int32)
        mask = jnp.arange(output.shape[0]) < batch_size
        wrong = jnp.where(mask, (pred != labels).astype(jnp.int32), 0)
        z = logits if logits is not None \
            else jnp.log(jnp.clip(output, 1e-30))
        out = {"n_err": jnp.sum(wrong),
               "loss_out": self.loss_from_logits(z, labels, batch_size)}
        if self.compute_confusion_matrix:
            n = output.shape[-1]
            onehot = (jnp.clip(labels, 0)[:, None] ==
                      jnp.arange(n)[None, :]).astype(jnp.int32)
            pred_onehot = (pred[:, None] ==
                           jnp.arange(n)[None, :]).astype(jnp.int32)
            cm = jnp.einsum("bi,bj->ij", onehot * mask[:, None].astype(
                jnp.int32), pred_onehot)
            out["confusion_matrix"] = cm.astype(jnp.int32)
        return out


class EvaluatorMSE(EvaluatorBase):
    """Mean-squared-error evaluator (znicz EvaluatorMSE): metrics are the
    batch mse and per-sample rmse."""

    WRITES = ("mse", "loss_out")

    def __init__(self, workflow, **kwargs):
        super(EvaluatorMSE, self).__init__(workflow, **kwargs)
        self.target = None       # linked from loader.minibatch_targets
        self.mse = Array()
        self.loss_out = Array()
        self.demand("target")

    @property
    def reads(self):
        return ("output", "target", "batch_size")

    def initialize(self, device=None, **kwargs):
        super(EvaluatorMSE, self).initialize(device=device, **kwargs)
        self.mse.reset(numpy.zeros((), numpy.float32))
        self.loss_out.reset(numpy.zeros((), numpy.float32))

    def loss(self, y, target, size):
        diff = (y.astype(jnp.float32)
                - target.astype(jnp.float32)).reshape(y.shape[0], -1)
        mask = (jnp.arange(y.shape[0]) < size)[:, None]
        return jnp.sum(jnp.where(mask, diff * diff, 0.0)) \
            / jnp.maximum(size, 1) / diff.shape[1]

    def step(self, output, target, batch_size):
        loss = self.loss(output, target, batch_size)
        return {"mse": loss, "loss_out": loss}


class EvaluatorNextToken(EvaluatorBase):
    """Per-token next-token cross-entropy — the actual language-model
    training objective (teacher forcing): logits [batch, seq, vocab]
    at position t are scored against token t+1 of the model's own
    INPUT, averaged over the seq-1 valid positions of the ``size``
    valid rows.  No reference analogue (the reference had no sequence
    dimension at all, SURVEY.md §5); this completes the LM stack the
    TPU rebuild adds: Embedding → TransformerBlock × N →
    TokenProjection → this evaluator.

    The trainer recognises ``TARGET_IS_INPUT`` and scores against the
    minibatch tokens (the labels channel is ignored), so any
    token-sequence loader works unchanged."""

    #: the trainer passes the model INPUT (the token minibatch) as the
    #: scoring target instead of the loader's labels
    TARGET_IS_INPUT = True

    WRITES = ("n_err", "loss_out")

    def __init__(self, workflow, **kwargs):
        super(EvaluatorNextToken, self).__init__(workflow, **kwargs)
        self.tokens = None       # linked from loader.minibatch_data
        self.n_err = Array()
        self.loss_out = Array()
        self.demand("tokens")

    @property
    def reads(self):
        return ("output", "tokens", "batch_size")

    def initialize(self, device=None, **kwargs):
        super(EvaluatorNextToken, self).initialize(device=device,
                                                   **kwargs)
        self.n_err.reset(numpy.zeros((), numpy.int32))
        self.loss_out.reset(numpy.zeros((), numpy.float32))

    @staticmethod
    def _shifted(logits, tokens):
        """(logits[:, :-1] f32, targets tokens[:, 1:])."""
        return (logits[:, :-1].astype(jnp.float32),
                tokens[:, 1:].astype(jnp.int32))

    def loss(self, y, tokens, size):
        """Mean CE per TOKEN over valid positions (rows < size)."""
        z, tgt = self._shifted(y, tokens)
        return masked_ce_from_logits(z, tgt, size,
                                     per_row_positions=tgt.shape[1])

    def metric_units(self, x):
        """Tokens scored per sample — the trainer's epoch accounting
        then divides by tokens, so validation_error_pct is the
        wrong-token percentage and validation_loss the per-token CE."""
        return x.shape[1] - 1

    def train_metrics(self, y, tokens, size):
        """Wrong next-token count over valid positions (the trainer's
        n_err hook — per-TOKEN granularity for min-tracking; the
        decision layer's error %% is then wrong-token %% × (seq-1))."""
        z, tgt = self._shifted(y, tokens)
        pred = jnp.argmax(z, axis=-1).astype(jnp.int32)
        mask = (jnp.arange(y.shape[0]) < size)[:, None]
        return jnp.sum(jnp.where(mask, (pred != tgt).astype(jnp.int32),
                                 0))

    def step(self, output, tokens, batch_size):
        return {
            "n_err": self.train_metrics(output, tokens, batch_size),
            "loss_out": self.loss(output, tokens, batch_size),
        }
