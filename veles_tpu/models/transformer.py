"""Transformer block — pre-LN causal attention + FFN with residuals,
as ONE forward unit (the trainer composes forwards linearly, so the
block keeps its residual adds internal; the unit graph stays
embedding → block × N → pool → head).

No reference analogue (sequence models never left the untested Znicz
submodule); this is the long-context-first-class stack the TPU rebuild
adds: the attention core is `ops.attention` (same math the
ring-attention sp path computes chip-locally), and the FFN can be a
top-k mixture of experts whose ``expert_*`` parameters shard over the
``ep`` mesh axis by the standard naming convention
(parallel/sharding.py).
"""

import jax
import jax.numpy as jnp
import numpy

from veles_tpu.memory import Array
from veles_tpu.models.nn_units import ForwardBase


def _dequant_dot(x, wq, scale, prec, ad):
    """Deferred-dequant matmul against a PRE-QUANTIZED int8
    checkpoint weight (``quantize_weights``): the int8 weight widens
    into the dot and the per-output-column f32 scale multiplies the
    accumulator.  Because the scale is a GLOBAL per-column constant
    (unlike the in-trace ``int8_decode`` epilogue, whose shard-local
    amax is layout-dependent), the dequant commutes with row-parallel
    partial sums — which is what lets int8 checkpoints serve under
    the tp mesh."""
    y = jnp.einsum("bsd,de->bse", x, wq.astype(x.dtype),
                   precision=prec, preferred_element_type=ad)
    return y * scale.astype(y.dtype)


def _layer_norm(x, scale, bias, eps=1e-5):
    xf = x.astype(jnp.float32)
    mean = xf.mean(axis=-1, keepdims=True)
    var = ((xf - mean) ** 2).mean(axis=-1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    return (y * scale.astype(jnp.float32)
            + bias.astype(jnp.float32)).astype(x.dtype)


class TransformerBlock(ForwardBase):
    """x -> x + MHA(LN(x)) -> + FFN(LN(.)), x: [batch, seq, d].

    ``n_experts`` switches the FFN to a top-k MoE (dense einsum
    dispatch, expert-major params on the ``ep`` axis)."""

    #: minibatch dim 1 is a SEQUENCE dim for this unit — the
    #: trainer sp-shards data dim 1 only when a forward says so
    #: (ADVICE.md r4 #2: sp sharding is opt-in)
    SEQ_DIM1_INPUT = True

    BASE_PARAMS = ("ln1_scale", "ln1_bias", "wq", "wk", "wv", "wo",
                   "ln2_scale", "ln2_bias")

    def __init__(self, workflow, heads=4, hidden=None, causal=True,
                 n_experts=0, top_k=2, attn_block_size=None,
                 attn_impl=None, int8_decode=False, **kwargs):
        super(TransformerBlock, self).__init__(workflow,
                                               include_bias=True,
                                               **kwargs)
        self.heads = int(heads)
        self.hidden = hidden  # None -> 4*d at fill time
        self.causal = bool(causal)
        #: stream K/V blockwise for long sequences (ops/attention.py)
        self.attn_block_size = attn_block_size
        #: attention core override: "flash" | "blockwise" | "dense"
        #: (None = auto; models/attention.mha_apply)
        self.attn_impl = attn_impl
        #: int8 weight-only matmuls for the DECODE-side MLP and
        #: output projection (ops/gemm.int8_matmul — per-column
        #: scales fused into the store epilogue).  Decode steps only:
        #: training/prefill keep the policy matmul.  Weights quantize
        #: inside the traced step (frozen serving params fold to
        #: constants under jit)
        self.int8_decode = bool(int8_decode)
        #: int8 CHECKPOINT weights (quantize_weights): the matmul
        #: weights are STORED int8 with per-output-column f32 scales
        #: as extra params — weight HBM halves at rest and on-device,
        #: every decode/prefill path dispatches on the stored dtype
        self.weights_int8 = False
        self.n_experts = int(n_experts)
        self.top_k = int(top_k)
        if self.n_experts and self.top_k > self.n_experts:
            raise ValueError("top_k %d > n_experts %d"
                             % (self.top_k, self.n_experts))
        if self.n_experts:
            self.PARAMS = self.BASE_PARAMS + (
                "gate", "expert_w1", "expert_b1", "expert_w2",
                "expert_b2")
        else:
            self.PARAMS = self.BASE_PARAMS + (
                "ffn_w1", "ffn_b1", "ffn_w2", "ffn_b2")
        for p in self.PARAMS:
            setattr(self, p, Array())

    def output_shape_for(self, input_shape):
        return tuple(input_shape)

    def fill_params(self):
        d = self.input.shape[-1]
        if d % self.heads:
            raise ValueError("model dim %d not divisible by %d heads"
                             % (d, self.heads))
        h = int(self.hidden or 4 * d)
        self.hidden = h
        for name in ("ln1_scale", "ln2_scale"):
            getattr(self, name).reset(numpy.ones((d,), numpy.float32))
        for name in ("ln1_bias", "ln2_bias"):
            getattr(self, name).reset(numpy.zeros((d,), numpy.float32))
        for name in ("wq", "wk", "wv", "wo"):
            arr = getattr(self, name)
            arr.reset(numpy.zeros((d, d), numpy.float32))
            self._fill(arr.mem, self.weights_filling,
                       self.weights_stddev, d, d)
        if self.n_experts:
            e = self.n_experts
            self.gate.reset(numpy.zeros((d, e), numpy.float32))
            self._fill(self.gate.mem, self.weights_filling,
                       self.weights_stddev, d, e)
            self.expert_w1.reset(numpy.zeros((e, d, h), numpy.float32))
            self.expert_w2.reset(numpy.zeros((e, h, d), numpy.float32))
            for w, fi, fo in ((self.expert_w1.mem, d, h),
                              (self.expert_w2.mem, h, d)):
                for i in range(e):
                    self._fill(w[i], self.weights_filling,
                               self.weights_stddev, fi, fo)
            self.expert_b1.reset(numpy.zeros((e, h), numpy.float32))
            self.expert_b2.reset(numpy.zeros((e, d), numpy.float32))
        else:
            self.ffn_w1.reset(numpy.zeros((d, h), numpy.float32))
            self._fill(self.ffn_w1.mem, self.weights_filling,
                       self.weights_stddev, d, h)
            self.ffn_b1.reset(numpy.zeros((h,), numpy.float32))
            self.ffn_w2.reset(numpy.zeros((h, d), numpy.float32))
            self._fill(self.ffn_w2.mem, self.weights_filling,
                       self.weights_stddev, h, d)
            self.ffn_b2.reset(numpy.zeros((d,), numpy.float32))

    # -- tensor-parallel serving layout (serving/tp.py) -----------------

    def tp_shardable(self, tp):
        """True when this block's Megatron layout divides over ``tp``
        shards: heads, model dim and FFN hidden all divisible (the
        head-wise K/V pool split and the column/row weight splits
        must land on whole heads / whole columns).  MoE FFNs shard
        over ``ep``, not ``tp`` (they opt out here), and the int8
        weight-only decode path quantizes per column INSIDE the trace
        — its dequant epilogue does not commute with the row-parallel
        partial sums, so it stays single-chip."""
        tp = int(tp)
        if tp < 2:
            return False
        if self.n_experts or self.int8_decode:
            return False
        d = self.wq.mem.shape[0]
        return self.heads % tp == 0 and d % tp == 0 \
            and int(self.hidden or 4 * d) % tp == 0

    def tp_param_spec(self, name, tp):
        """Megatron-style spec for one parameter under a ``tp`` mesh
        axis, or None (replicate): wq/wk/wv and the FFN up-projection
        are COLUMN-parallel (each shard owns whole heads / hidden
        columns, so attention and the activation stay chip-local),
        wo and the FFN down-projection ROW-parallel (their outputs
        are the per-layer cross-chip reductions XLA inserts).  LN
        scales and the output-side biases replicate — they apply
        after the reduction."""
        from jax.sharding import PartitionSpec as P
        if not self.tp_shardable(tp):
            return None
        if name in ("wq", "wk", "wv", "ffn_w1"):
            return P(None, "tp")
        if name in ("wo", "ffn_w2"):
            return P("tp", None)
        if name == "ffn_b1":
            return P("tp")
        # int8-checkpoint dequant scales (quantize_weights): per
        # OUTPUT column, so they split with column-parallel weights
        # and replicate beside row-parallel ones (their outputs keep
        # the full model dim)
        if name in ("wq_scale", "wk_scale", "wv_scale",
                    "ffn_w1_scale"):
            return P("tp")
        return None

    # -- int8 weight checkpoints (snapshotter weights_dtype) ------------

    def quantize_weights(self):
        """Re-store this block's matmul weights in the int8 CHECKPOINT
        format: per-output-column symmetric absmax quantization
        (``ops/gemm.int8_weight_quantize`` — the same scales the
        in-trace decode epilogue computes), the int8 tensor REPLACING
        the f32 one in place and a ``{name}_scale`` f32 vector
        joining ``PARAMS`` beside it.  Weight bytes halve at rest, in
        the snapshot AND in device HBM — unlike ``int8_decode``,
        which re-quantizes from resident f32 weights inside the
        trace.  Every decode/prefill/verify path dispatches on the
        stored dtype (``_dequant_dot``), and the global per-column
        scales commute with the tp row-parallel partial sums, so
        quantized checkpoints still shard.  Idempotent; MoE blocks
        (expert-sharded weights) are not supported."""
        if self.n_experts:
            raise ValueError(
                "int8 weight checkpoints need the dense FFN (MoE "
                "expert weights shard over ep; not supported)")
        if getattr(self, "weights_int8", False):
            return
        from veles_tpu.ops import gemm
        names = ("wq", "wk", "wv", "wo", "ffn_w1", "ffn_w2")
        for name in names:
            arr = getattr(self, name)
            arr.map_read()
            wq, scale = gemm.int8_weight_quantize(
                jnp.asarray(arr.mem, jnp.float32))
            arr.reset(numpy.asarray(wq))
            sarr = Array(numpy.asarray(scale, numpy.float32))
            dev = getattr(self, "device", None)
            if dev is not None:
                sarr.initialize(dev)
            setattr(self, name + "_scale", sarr)
        self.PARAMS = tuple(self.PARAMS) \
            + tuple(n + "_scale" for n in names)
        self.weights_int8 = True

    def _mha(self, params, x):
        from veles_tpu.models.attention import mha_apply
        dev = getattr(self, "device", None)
        return mha_apply(
            {k: params[k] for k in ("wq", "wk", "wv", "wo")}, x,
            self.heads, self.causal, self.attn_block_size,
            sp_mesh=getattr(self, "sp_mesh_", None),
            attn_impl=getattr(self, "attn_impl", None),
            backend=dev.jax_device.platform if dev else None)

    def _w8_matmul(self, x, w):
        """Weight-only int8 matmul of a decode activation ``x``
        [b, s, d1] by ``w`` [d1, d2]: quantize per output column,
        accumulate int8 products, dequant fused in the epilogue
        (ops/gemm.py).  Returns [b, s, d2] f32."""
        from veles_tpu import dtypes
        from veles_tpu.ops import gemm
        b, s, d1 = x.shape
        wq, scale = gemm.int8_weight_quantize(w)
        dev = getattr(self, "device", None)
        out = gemm.int8_matmul(
            x.reshape(b * s, d1).astype(dtypes.compute_dtype()),
            wq, scale,
            backend=dev.jax_device.platform if dev else None)
        return out.reshape(b, s, -1)

    def _ffn(self, params, x, w8=False):
        from veles_tpu import dtypes
        cd = dtypes.compute_dtype()
        if self.n_experts:
            from veles_tpu.models.moe import moe_apply
            return moe_apply(params, x, self.top_k, "strict_relu")
        if w8:   # decode-side weight-only int8 (see int8_decode)
            h1 = self._w8_matmul(x, params["ffn_w1"])
            h1 = jnp.maximum(
                h1 + params["ffn_b1"].astype(jnp.float32),
                0.0).astype(cd)
            y = self._w8_matmul(h1, params["ffn_w2"])
            return (y + params["ffn_b2"].astype(
                jnp.float32)).astype(x.dtype)
        if params["ffn_w1"].dtype == jnp.int8:   # int8 checkpoint
            h1 = jnp.einsum("bsd,dh->bsh", x.astype(cd),
                            params["ffn_w1"].astype(cd),
                            preferred_element_type=jnp.float32) \
                * params["ffn_w1_scale"].astype(jnp.float32)
        else:
            h1 = jnp.einsum("bsd,dh->bsh", x.astype(cd),
                            params["ffn_w1"].astype(cd),
                            preferred_element_type=jnp.float32)
        h1 = jnp.maximum(
            h1 + params["ffn_b1"].astype(jnp.float32), 0.0).astype(cd)
        if params["ffn_w2"].dtype == jnp.int8:   # int8 checkpoint
            y = jnp.einsum("bsh,hd->bsd", h1,
                           params["ffn_w2"].astype(cd),
                           preferred_element_type=jnp.float32) \
                * params["ffn_w2_scale"].astype(jnp.float32)
        else:
            y = jnp.einsum("bsh,hd->bsd", h1,
                           params["ffn_w2"].astype(cd),
                           preferred_element_type=jnp.float32)
        return (y + params["ffn_b2"].astype(jnp.float32)).astype(x.dtype)

    def apply(self, params, x):
        h = x + self._mha(params, _layer_norm(
            x, params["ln1_scale"], params["ln1_bias"]))
        return h + self._ffn(params, _layer_norm(
            h, params["ln2_scale"], params["ln2_bias"]))

    # -- single-token decode (models/generate.py kv_cache path) ---------

    def init_cache(self, batch, max_len, dtype):
        """Zeroed K/V decode buffers, [batch, max_len, d] each (d from
        the filled ``wq``; rows are written by :meth:`apply_step`)."""
        d = self.wq.mem.shape[0]
        return {"k": jnp.zeros((batch, max_len, d), dtype),
                "v": jnp.zeros((batch, max_len, d), dtype)}

    def _qkv(self, params, x):
        """LN1 + q/k/v projections in the decode conventions (the
        projection dtypes apply_step documents — shared by the
        single-token, per-slot and batched-prefill steps so all three
        produce identical K/V rows)."""
        from veles_tpu import dtypes
        cd = dtypes.compute_dtype()
        ad = dtypes.accum_dtype()
        prec = dtypes.matmul_precision()
        ln = _layer_norm(x, params["ln1_scale"], params["ln1_bias"])

        def proj(name):
            w = params[name]
            if w.dtype == jnp.int8:   # int8 checkpoint weight
                y = _dequant_dot(ln.astype(cd), w,
                                 params[name + "_scale"], prec, ad)
            else:
                y = jnp.einsum("bsd,de->bse", ln.astype(cd),
                               w.astype(cd), precision=prec,
                               preferred_element_type=ad)
            return y.astype(cd)

        return proj("wq"), proj("wk"), proj("wv")

    def _attn_tail(self, params, x, o, w8=False):
        """Output projection + residual + FFN half over an attention
        context ``o`` [b, s, d] (the shared tail of every decode-step
        variant; the paged step computes ``o`` in
        ``ops.paged_attention``).  ``w8`` switches the projection and
        MLP to the int8 weight-only path (decode steps with
        ``int8_decode`` set)."""
        from veles_tpu import dtypes
        cd = dtypes.compute_dtype()
        ad = dtypes.accum_dtype()
        prec = dtypes.matmul_precision()
        if w8:
            attn = self._w8_matmul(o, params["wo"]).astype(x.dtype)
        elif params["wo"].dtype == jnp.int8:   # int8 checkpoint
            attn = _dequant_dot(o.astype(cd), params["wo"],
                                params["wo_scale"], prec,
                                ad).astype(x.dtype)
        else:
            attn = jnp.einsum("bsd,de->bse", o.astype(cd),
                              params["wo"].astype(cd), precision=prec,
                              preferred_element_type=ad).astype(x.dtype)
        y = x + attn
        return y + self._ffn(params, _layer_norm(
            y, params["ln2_scale"], params["ln2_bias"]), w8=w8)

    def _attn_out(self, params, x, probs, vh):
        """probs·V + the shared tail."""
        b, s, d = x.shape
        o = jnp.einsum("bhqk,bkhd->bqhd", probs, vh).reshape(b, s, d)
        return self._attn_tail(params, x, o)

    def apply_prefill(self, params, x, cache, lens=None):
        """Batched prompt prefill: consume ALL of x [batch, P, d] in
        ONE pass, writing every position's K/V into cache rows
        [0, P) — the O(1)-compiled-steps replacement for scanning
        :meth:`apply_step` over the prompt.  Same projection/attention
        conventions as apply_step, so the cache rows and outputs match
        the per-token scan (f32).

        ``lens`` (optional [batch] ints, traced): ragged prompts —
        K/V rows at or past each row's length are ZEROED (exactly the
        rows a per-row sequential prefill would have left at the
        init_cache zeros), and output rows past the length are
        garbage the caller must not read.  Valid rows are unaffected:
        the causal mask keeps queries q < lens[n] away from the
        zeroed keys."""
        from veles_tpu import dtypes
        cd = dtypes.compute_dtype()
        b, p, d = x.shape
        h = self.heads
        hd = d // h
        q, k_new, v_new = self._qkv(params, x)
        if lens is not None:
            keep = (jnp.arange(p)[None, :] < lens[:, None])[..., None]
            k_new = jnp.where(keep, k_new, 0).astype(k_new.dtype)
            v_new = jnp.where(keep, v_new, 0).astype(v_new.dtype)
        ck = jax.lax.dynamic_update_slice(
            cache["k"], k_new.astype(cache["k"].dtype), (0, 0, 0))
        cv = jax.lax.dynamic_update_slice(
            cache["v"], v_new.astype(cache["v"].dtype), (0, 0, 0))
        qh = q.reshape(b, p, h, hd)
        kh = k_new.astype(cd).reshape(b, p, h, hd)
        vh = v_new.astype(cd).reshape(b, p, h, hd)
        logits = jnp.einsum("bqhd,bkhd->bhqk", qh, kh) \
            * (1.0 / jnp.sqrt(hd))
        mask = (jnp.arange(p)[None, :]
                <= jnp.arange(p)[:, None])[None, None]
        logits = jnp.where(mask, logits, -jnp.inf)
        probs = jax.nn.softmax(logits, axis=-1)
        return self._attn_out(params, x, probs, vh), \
            {"k": ck, "v": cv}

    def apply_prefill_chunk(self, params, x, cache, offset,
                            chunk_lens=None, key_width=None):
        """CHUNKED prefill continuation: consume x [b, C, d] — the
        prompt's positions [offset, offset+C) (``offset`` a traced
        scalar, a multiple of C) — writing the chunk's K/V into cache
        rows [offset, offset+C) and attending each query over cached
        keys [0, key_width) with the causal mask ``key ≤ offset + q``.
        Chunk-for-chunk the same math as :meth:`apply_prefill` (which
        is the offset-0, single-chunk special case), so running the
        chunks sequentially reproduces the one-shot cache rows and
        last-position logits.

        ``chunk_lens`` (optional [b] ints, traced): rows whose prompt
        ends inside this chunk — K/V rows at or past
        ``offset + chunk_lens[n]`` are ZEROED (matching the staging
        cache's init zeros) and output rows past the length are
        garbage the caller must not read.  ``key_width`` (static int,
        default the cache width) bounds the attended key range — the
        caller buckets it to a power of two ≥ offset + C so shallow
        chunks don't pay full-window attention."""
        from veles_tpu import dtypes
        cd = dtypes.compute_dtype()
        b, c, d = x.shape
        h = self.heads
        hd = d // h
        q, k_new, v_new = self._qkv(params, x)
        if chunk_lens is not None:
            keep = (jnp.arange(c)[None, :]
                    < chunk_lens[:, None])[..., None]
            k_new = jnp.where(keep, k_new, 0).astype(k_new.dtype)
            v_new = jnp.where(keep, v_new, 0).astype(v_new.dtype)
        ck = jax.lax.dynamic_update_slice(
            cache["k"], k_new.astype(cache["k"].dtype),
            (jnp.int32(0), offset, jnp.int32(0)))
        cv = jax.lax.dynamic_update_slice(
            cache["v"], v_new.astype(cache["v"].dtype),
            (jnp.int32(0), offset, jnp.int32(0)))
        kw = int(key_width or ck.shape[1])
        qh = q.reshape(b, c, h, hd)
        kh = ck[:, :kw].astype(cd).reshape(b, kw, h, hd)
        vh = cv[:, :kw].astype(cd).reshape(b, kw, h, hd)
        logits = jnp.einsum("bqhd,bkhd->bhqk", qh, kh) \
            * (1.0 / jnp.sqrt(hd))
        mask = (jnp.arange(kw)[None, :]
                <= (offset + jnp.arange(c))[:, None])[None, None]
        logits = jnp.where(mask, logits, -jnp.inf)
        probs = jax.nn.softmax(logits, axis=-1)
        return self._attn_out(params, x, probs, vh), \
            {"k": ck, "v": cv}

    def init_block_pool(self, num_blocks, block_size, dtype,
                        kv_dtype="fp32"):
        """Zeroed paged K/V pools, [num_blocks, block_size, d] each —
        the block-granular counterpart of :meth:`init_cache` (see
        serving/kv_slots.PagedKVCache).  ``kv_dtype="int8"`` stores
        the pools as int8 with per-row f32 dequant scales
        ([num_blocks, block_size], keys ``k_scale``/``v_scale``)
        living beside them — zero scales make the trash block's
        garbage dequantize to exact 0.0."""
        base = self.init_cache(num_blocks, block_size, dtype)
        if kv_dtype == "fp32":
            return base
        if kv_dtype != "int8":
            raise ValueError("kv_dtype must be 'fp32' or 'int8'")
        return {
            "k": jnp.zeros(base["k"].shape, jnp.int8),
            "v": jnp.zeros(base["v"].shape, jnp.int8),
            "k_scale": jnp.zeros((num_blocks, block_size),
                                 jnp.float32),
            "v_scale": jnp.zeros((num_blocks, block_size),
                                 jnp.float32),
        }

    def _backend(self):
        dev = getattr(self, "device", None)
        return dev.jax_device.platform if dev else None

    def apply_step_paged(self, params, x, pos, tables, pool):
        """Decode ONE position PER ROW against a PAGED KV pool: x
        [batch, 1, d] with row n at sequence index ``pos[n]``, reading
        and writing through ``tables`` [batch, T] physical block ids
        (serving/kv_slots.PagedKVCache).  Row-for-row the same math as
        :meth:`apply_step_slots` restricted to the gathered blocks —
        greedy token parity with the dense slot cache is tested.  An
        INT8 pool (``k_scale`` beside the buffers) quantizes the new
        row on the scatter and dequantizes fused into the gather
        (ops/paged_attention.py q8 paths; the pallas kernel on
        accelerator targets)."""
        from veles_tpu.ops.paged_attention import (
            paged_decode_attention, paged_decode_attention_q8)
        q, k_new, v_new = self._qkv(params, x)
        w8 = self.int8_decode
        if "k_scale" in pool:
            pk, pv, sk, sv, o = paged_decode_attention_q8(
                q, k_new, v_new, pool["k"], pool["v"],
                pool["k_scale"], pool["v_scale"], tables, pos,
                self.heads, backend=self._backend())
            return self._attn_tail(params, x, o, w8=w8), \
                {"k": pk, "v": pv, "k_scale": sk, "v_scale": sv}
        pk, pv, o = paged_decode_attention(
            q, k_new, v_new, pool["k"], pool["v"], tables, pos,
            self.heads)
        return self._attn_tail(params, x, o, w8=w8), \
            {"k": pk, "v": pv}

    def apply_step_paged_local(self, params, x, pos, tables, pool,
                               tp):
        """PER-SHARD decode step body for the collective-overlap tp
        path (``engine._make_paged_step_tp`` runs it under shard_map
        over the ``tp`` mesh axis): ``params`` are this shard's
        Megatron slices (wq/wk/wv/ffn_w1 column slices → local heads
        and hidden columns, wo/ffn_w2 row slices), ``pool`` this
        shard's head-wise K/V slice.  Identical math to
        :meth:`apply_step_paged` — the two GSPMD-implicit per-layer
        reductions become EXPLICIT ``tp_allreduce`` calls
        (serving/tp.py) the compiler can issue asynchronously while
        the pool writeback proceeds.  fp32 pools only (the int8
        per-row amax must span the full feature axis)."""
        from veles_tpu import dtypes
        from veles_tpu.ops.paged_attention import paged_decode_attention
        from veles_tpu.serving.tp import tp_allreduce
        cd = dtypes.compute_dtype()
        ad = dtypes.accum_dtype()
        prec = dtypes.matmul_precision()
        heads_local = self.heads // int(tp)
        q, k_new, v_new = self._qkv(params, x)
        pk, pv, o = paged_decode_attention(
            q, k_new, v_new, pool["k"], pool["v"], tables, pos,
            heads_local)
        # row-parallel output projection: the partial sum reduces
        # EXPLICITLY — issued before the residual/FFN consume it, so
        # the cross-chip hop can overlap the pool scatter above
        if params["wo"].dtype == jnp.int8:   # int8 checkpoint
            partial = _dequant_dot(o.astype(cd), params["wo"],
                                   params["wo_scale"], prec, ad)
        else:
            partial = jnp.einsum("bsd,de->bse", o.astype(cd),
                                  params["wo"].astype(cd),
                                  precision=prec,
                                  preferred_element_type=ad)
        attn = tp_allreduce(partial, "tp", int(tp)).astype(x.dtype)
        y = x + attn
        ln2 = _layer_norm(y, params["ln2_scale"], params["ln2_bias"])
        if params["ffn_w1"].dtype == jnp.int8:
            h1 = jnp.einsum("bsd,dh->bsh", ln2.astype(cd),
                            params["ffn_w1"].astype(cd),
                            preferred_element_type=jnp.float32) \
                * params["ffn_w1_scale"].astype(jnp.float32)
        else:
            h1 = jnp.einsum("bsd,dh->bsh", ln2.astype(cd),
                            params["ffn_w1"].astype(cd),
                            preferred_element_type=jnp.float32)
        h1 = jnp.maximum(
            h1 + params["ffn_b1"].astype(jnp.float32), 0.0).astype(cd)
        if params["ffn_w2"].dtype == jnp.int8:
            p2 = jnp.einsum("bsh,hd->bsd", h1,
                            params["ffn_w2"].astype(cd),
                            preferred_element_type=jnp.float32) \
                * params["ffn_w2_scale"].astype(jnp.float32)
        else:
            p2 = jnp.einsum("bsh,hd->bsd", h1,
                            params["ffn_w2"].astype(cd),
                            preferred_element_type=jnp.float32)
        ffn = tp_allreduce(p2, "tp", int(tp))
        out = y + (ffn + params["ffn_b2"].astype(
            jnp.float32)).astype(x.dtype)
        return out, {"k": pk, "v": pv}

    def apply_verify_paged(self, params, x, pos, lens, tables, pool):
        """Speculative-decoding VERIFY step: score a width-K1 token
        run per row — x [batch, K1, d], row n's position j at
        sequence index ``pos[n] + j``, ``lens`` [batch] marking how
        many positions are real (padding scatters to the trash
        block) — against the paged pool in ONE pass.  Position-for-
        position the same math as :meth:`apply_step_paged` (its
        K1 = 1 special case), so accepting the matched prefix of the
        scored run reproduces sequential decode exactly.

        INT8 pools always take the fused q8 verify (quantizing
        scatter + dequant-fused attend); fp32 pools take the PR 9
        two-pass path unless ``root.common.serving.fused_verify`` is
        set — the fused single-pass variant is allclose, not
        bit-identical, so the parity baseline stays two-pass."""
        from veles_tpu.ops.paged_attention import (
            paged_verify_attention, paged_verify_attention_fused,
            paged_verify_attention_q8)
        q, k_new, v_new = self._qkv(params, x)
        w8 = self.int8_decode
        if "k_scale" in pool:
            pk, pv, sk, sv, o = paged_verify_attention_q8(
                q, k_new, v_new, pool["k"], pool["v"],
                pool["k_scale"], pool["v_scale"], tables, pos, lens,
                self.heads, backend=self._backend())
            return self._attn_tail(params, x, o, w8=w8), \
                {"k": pk, "v": pv, "k_scale": sk, "v_scale": sv}
        from veles_tpu.config import root
        if root.common.serving.get("fused_verify", False):
            pk, pv, o = paged_verify_attention_fused(
                q, k_new, v_new, pool["k"], pool["v"], tables, pos,
                lens, self.heads, backend=self._backend())
        else:
            pk, pv, o = paged_verify_attention(
                q, k_new, v_new, pool["k"], pool["v"], tables, pos,
                lens, self.heads)
        return self._attn_tail(params, x, o, w8=w8), \
            {"k": pk, "v": pv}

    def apply_step_slots(self, params, x, pos, cache):
        """Decode ONE position PER ROW: x [batch, 1, d] where row n
        sits at ITS OWN sequence index ``pos[n]`` ([batch] ints,
        traced) — the serving-slot shape: requests at different decode
        depths share one compiled step.  Row-for-row the same math as
        :meth:`apply_step` (which is the all-pos-equal special case):
        K/V written at ``pos[n]``, attention over keys ≤ ``pos[n]``."""
        from veles_tpu import dtypes
        cd = dtypes.compute_dtype()
        b, _, d = x.shape
        h = self.heads
        hd = d // h
        q, k_new, v_new = self._qkv(params, x)
        rows = jnp.arange(b)
        ck = cache["k"].at[rows, pos].set(
            k_new[:, 0].astype(cache["k"].dtype))
        cv = cache["v"].at[rows, pos].set(
            v_new[:, 0].astype(cache["v"].dtype))
        length = ck.shape[1]
        qh = q.reshape(b, 1, h, hd)
        kh = ck.astype(cd).reshape(b, length, h, hd)
        vh = cv.astype(cd).reshape(b, length, h, hd)
        logits = jnp.einsum("bqhd,bkhd->bhqk", qh, kh) \
            * (1.0 / jnp.sqrt(hd))
        mask = (jnp.arange(length)[None, :]
                <= pos[:, None])[:, None, None, :]
        logits = jnp.where(mask, logits, -jnp.inf)
        probs = jax.nn.softmax(logits, axis=-1)
        return self._attn_out(params, x, probs, vh), \
            {"k": ck, "v": cv}

    def apply_step(self, params, x, pos, cache):
        """Decode ONE position: x [batch, 1, d] at sequence index
        ``pos`` (traced scalar); returns (y, cache') with this step's
        K/V written into the cache — O(max_len) work per token vs
        re-running :meth:`apply` over the whole buffer (O(seq²)).
        Exact for causal blocks: cache rows past ``pos`` hold zeros
        that the mask excludes.  Mirrors mha_apply's dense-core
        conventions (projection dtypes, 1/sqrt(hd) scaling, softmax
        over the key axis) so greedy decode is token-for-token
        identical in f32."""
        from veles_tpu import dtypes
        cd = dtypes.compute_dtype()
        b, _, d = x.shape
        h = self.heads
        hd = d // h
        q, k_new, v_new = self._qkv(params, x)
        ck = jax.lax.dynamic_update_slice(
            cache["k"], k_new.astype(cache["k"].dtype), (0, pos, 0))
        cv = jax.lax.dynamic_update_slice(
            cache["v"], v_new.astype(cache["v"].dtype), (0, pos, 0))
        length = ck.shape[1]
        qh = q.reshape(b, 1, h, hd)
        kh = ck.astype(cd).reshape(b, length, h, hd)
        vh = cv.astype(cd).reshape(b, length, h, hd)
        logits = jnp.einsum("bqhd,bkhd->bhqk", qh, kh) \
            * (1.0 / jnp.sqrt(hd))
        mask = (jnp.arange(length) <= pos)[None, None, None, :]
        logits = jnp.where(mask, logits, -jnp.inf)
        probs = jax.nn.softmax(logits, axis=-1)
        return self._attn_out(params, x, probs, vh), \
            {"k": ck, "v": cv}

    def export_config(self):
        cfg = {"heads": self.heads, "hidden": int(self.hidden),
               "causal": self.causal, "n_experts": self.n_experts,
               "top_k": self.top_k}
        if self.attn_block_size:  # v2 key — omit when unused
            cfg["attn_block_size"] = int(self.attn_block_size)
        if self.attn_impl:  # an explicit core pin must survive export
            cfg["attn_impl"] = self.attn_impl
        if self.int8_decode:  # v2 key — omit when unused
            cfg["int8_decode"] = True
        if getattr(self, "weights_int8", False):  # v3 key — the
            # int8-checkpoint trace differs; the flag keys _arch_sig
            cfg["weights_int8"] = True
        return cfg


class MeanPoolSeq(ForwardBase):
    """[batch, seq, d] -> [batch, d] mean over the sequence axis."""

    PARAMS = ()

    def fill_params(self):
        pass

    def output_shape_for(self, input_shape):
        return (input_shape[0], input_shape[-1])

    def apply(self, params, x):
        return x.mean(axis=1)

    def export_config(self):
        return {}


class TokenProjection(ForwardBase):
    """Per-token logits head: [batch, seq, d] → [batch, seq, vocab]
    (the LM head — scored per position by EvaluatorNextToken; the
    pooled classifier head remains ``mean_pool_seq`` + softmax).
    With a ``tp`` mesh axis the vocab dim column-shards by the
    standard convention (parallel/sharding.py)."""

    PARAMS = ("weights", "bias")
    SEQ_DIM1_INPUT = True
    #: position-wise: safe to apply to a [batch, 1, d] decode step
    #: unchanged (models/generate.py kv_cache chain dispatch)
    DECODE_POINTWISE = True

    def __init__(self, workflow, vocab=None, **kwargs):
        super(TokenProjection, self).__init__(workflow,
                                              include_bias=True,
                                              **kwargs)
        if vocab is None:
            raise ValueError("vocab is required")
        self.vocab = int(vocab)

    def output_shape_for(self, input_shape):
        return tuple(input_shape[:-1]) + (self.vocab,)

    def fill_params(self):
        d = self.input.shape[-1]
        self.weights.reset(numpy.zeros((d, self.vocab), numpy.float32))
        self._fill(self.weights.mem, self.weights_filling,
                   self.weights_stddev, d, self.vocab)
        self.bias.reset(numpy.zeros((self.vocab,), numpy.float32))

    def apply(self, params, x):
        from veles_tpu import dtypes
        cd = dtypes.compute_dtype()
        y = jnp.einsum("bsd,dv->bsv", x.astype(cd),
                       params["weights"].astype(cd),
                       precision=dtypes.matmul_precision(),
                       preferred_element_type=jnp.float32)
        # logits stay f32: the CE loss needs full precision and the
        # [b, s, vocab] tensor is the last thing the chain produces
        return y + params["bias"].astype(jnp.float32)

    def export_config(self):
        return {"vocab": self.vocab}
