"""GradientDescent — the fused autodiff trainer.

TPU-native replacement for the reference's per-layer backward units
(znicz gd*.py with hand-derived CUDA/OpenCL gradient kernels; surface per
manualrst_veles_algorithms.rst items 5, 8, 9, 11, 13).  One unit owns the
whole training step:

    loss = evaluator.loss(forward_chain(params, x), target)
    grads = jax.grad(loss)          # replaces every hand-written kernel
    params = solver.update(...)     # sgd/momentum/adagrad/adadelta/adam

— all traced into ONE jitted XLA program with parameters and solver state
donated (in-place HBM update).  Validation/test minibatches flow through
the same program: ``lax.cond`` on the minibatch class skips the update
while still returning loss/n_err, so there is exactly one compiled
executable for the whole train/eval cycle.

Per-layer hyper-parameter overrides (extras item 13) resolve at trace
time from each forward unit's attributes; the learning-rate schedule
(lr_adjust) is traced on the global step; when the workflow runs under a
device mesh the gradient ``psum`` over the ``dp`` axis happens inside
this same program (see veles_tpu.parallel).
"""

import jax
import jax.numpy as jnp
import numpy

from veles_tpu.accelerated_units import AcceleratedUnit
from veles_tpu.loader.base import TRAIN
from veles_tpu.memory import Array
from veles_tpu.models.all2all import All2AllSoftmax
from veles_tpu.models.dropout import DropoutForward
from veles_tpu.models.evaluator import EvaluatorMSE
from veles_tpu.models.lr_adjust import get_schedule
from veles_tpu.models.solvers import get_solver
from veles_tpu import prng as prng_mod


class GradientDescent(AcceleratedUnit):
    """The trainer unit (replaces a whole chain of znicz GD units)."""

    VIEW_GROUP = "TRAINER"
    FUSABLE = False  # self-jits with donation; owns its own dispatch

    def __init__(self, workflow, forwards=None, evaluator=None, loader=None,
                 solver="sgd", learning_rate=0.01, learning_rate_bias=None,
                 weights_decay=0.0, weights_decay_bias=None, l1_vs_l2=0.0,
                 gradient_moment=0.0, gradient_moment_bias=None,
                 lr_schedule="constant", lr_schedule_params=None,
                 prng_key="trainer", mesh=None, augment=None,
                 pp_microbatches=None, **kwargs):
        super(GradientDescent, self).__init__(workflow, **kwargs)
        #: jax.sharding.Mesh — when set, the fused step is sharded over
        #: it (dp batch split + psum, tp weight split; see
        #: veles_tpu.parallel.sharding).  Replaces the reference's entire
        #: ZeroMQ master-slave gradient exchange (SURVEY.md §2.3).
        self.mesh = mesh
        self.forwards = list(forwards) if forwards else []
        self.evaluator = evaluator
        self.loader = loader
        self.solver_name = solver
        self.learning_rate = learning_rate
        self.learning_rate_bias = learning_rate_bias \
            if learning_rate_bias is not None else learning_rate
        self.weights_decay = weights_decay
        self.weights_decay_bias = weights_decay_bias \
            if weights_decay_bias is not None else weights_decay
        self.l1_vs_l2 = l1_vs_l2
        self.gradient_moment = gradient_moment
        self.gradient_moment_bias = gradient_moment_bias \
            if gradient_moment_bias is not None else gradient_moment
        self.lr_schedule = lr_schedule
        self.lr_schedule_params = lr_schedule_params or {}
        #: in-graph train-time augmentation traced into the fused step
        #: (ops/augment.py); eval sees clean data.  A dict spec like
        #: {"kind": "image", "pad": 4} survives snapshots (a raw
        #: callable works too but won't pickle)
        self.augment = augment
        #: microbatches per pipeline step on a ``pp`` mesh (None →
        #: the pp extent; larger shrinks the bubble fraction
        #: (S-1)/(M+S-1) at the cost of smaller per-stage matmuls)
        self.pp_microbatches = pp_microbatches
        self.prng = prng_mod.get(prng_key)
        self.lr_multiplier = 1.0  # Rollback adjusts this

        self.global_step = 0
        self.opt_state = {}      # {layer_idx: {param: {slot: Array}}}
        self.loss = Array()
        self.n_err = Array()
        #: device-side per-class epoch accumulator [class, (n_err,
        #: loss_sum, samples)] — DecisionGD reads it once per epoch
        #: instead of syncing on every minibatch
        self.epoch_acc = Array()
        self.demand("forwards", "evaluator", "loader")

    def __getstate__(self):
        state = super(GradientDescent, self).__getstate__()
        if state.get("mesh") is not None \
                and not isinstance(state["mesh"], dict):
            # a jax Mesh holds Device objects — unpicklable.  Persist
            # the concrete AXIS SPEC; initialize() rebuilds the mesh
            # over the resuming process's devices (which must supply a
            # matching chip count — to re-shard onto a different
            # topology, override .mesh before initialize).  A not-yet-
            # initialized restore re-pickles the spec dict as-is.
            state["mesh"] = {"__mesh_axes__": dict(state["mesh"].shape)}
        return state

    def init_unpickled(self):
        super(GradientDescent, self).init_unpickled()
        self._train_step_ = None
        self._span_step_ = None
        self._shardings_ = None
        self._pp_plan_ = None
        #: master-side epoch accumulator in float64: the master's device
        #: program never runs, and f32 accumulation of worker sample
        #: counts stops being exact past ~2^24 samples/epoch — the
        #: epoch-completion threshold would never fire (a hang).
        #: Volatile: resume abandons in-flight accounting, like
        #: pending_minibatches_ (ref: base.py:205).
        self._master_acc_ = numpy.zeros((3, 3), numpy.float64)

    # -- hyper-parameter resolution (extras item 13) ---------------------------

    def _layer_hp(self, unit, param_name):
        hp = unit.hyperparams()

        def pick(specific, generic, default):
            v = hp.get(specific)
            if v is None:
                v = hp.get(generic)
            return default if v is None else v

        if param_name == "bias":
            return {
                "lr": pick("learning_rate_bias", "learning_rate",
                           self.learning_rate_bias),
                "decay": pick("weights_decay_bias", "weights_decay",
                              self.weights_decay_bias),
                "moment": pick("gradient_moment_bias", "gradient_moment",
                               self.gradient_moment_bias),
                "l1_vs_l2": self.l1_vs_l2,
            }
        return {
            "lr": pick("learning_rate", None, self.learning_rate),
            "decay": pick("weights_decay", None, self.weights_decay),
            "moment": pick("gradient_moment", None, self.gradient_moment),
            "l1_vs_l2": self.l1_vs_l2,
        }

    # -- lifecycle -------------------------------------------------------------

    def initialize(self, device=None, **kwargs):
        from veles_tpu.units import MissingDemand
        if isinstance(self.mesh, dict):
            # an axis-spec dict — a snapshot restore (__getstate__'s
            # sentinel form) or a user override like {'dp': 4} — is
            # materialized here: over ALL processes' devices for a
            # multi-host gang, over the target device's backend
            # otherwise (build_mesh raises a clear error on a
            # mismatched chip count)
            import jax
            axes = self.mesh.get("__mesh_axes__", self.mesh)
            if jax.process_count() > 1:
                # a gang spans every process's chips — but still on
                # the target device's PLATFORM (a numpy-backend run on
                # a GPU-default host must not grab GPU devices)
                from veles_tpu.parallel import build_mesh
                self.mesh = build_mesh(dict(axes), devices=jax.devices(
                    device.jax_device.platform) if device is not None
                    else None)
            elif device is not None:
                self.mesh = device.make_mesh(axes)
            else:
                from veles_tpu.parallel import build_mesh
                self.mesh = build_mesh(dict(axes))
        if not self.forwards or self.evaluator is None \
                or self.loader is None:
            raise MissingDemand(self, {"forwards", "evaluator", "loader"})
        for u in self.forwards:
            if not u.is_initialized:
                raise MissingDemand(self, {"forwards[%s]" % u.name})
        if isinstance(self.evaluator, EvaluatorMSE) \
                and getattr(self.loader, "minibatch_targets", None) is None:
            raise MissingDemand(self, {"loader.minibatch_targets"})
        if self.mesh is not None and self.mesh.shape.get("pp", 1) > 1:
            self._pp_plan_ = self._make_pp_plan()
        if self.mesh is not None \
                and self.mesh.shape.get("sp", 1) > 1:
            # sequence parallelism is a COMMUNICATION SCHEDULE, not a
            # sharding GSPMD can derive: hand each forward the mesh so
            # attention units switch to the ppermute ring
            # (models/attention.mha_apply).  Volatile (trailing _) —
            # re-established here on every snapshot resume.
            for u in self.forwards:
                u.sp_mesh_ = self.mesh
        solver = get_solver(self.solver_name)
        if not self.opt_state:  # fresh (not restored from snapshot)
            for i, u in enumerate(self.forwards):
                per_param = {}
                for name, arr in u.param_arrays().items():
                    # init on device from the already-uploaded param —
                    # no host round-trip (solver slots are zeros_like;
                    # pulling them to host and re-uploading costs 2×
                    # model size over the host↔HBM link)
                    slots = solver.init(arr.devmem)
                    per_param[name] = {}
                    for s, v in slots.items():
                        a = Array()
                        a.devmem = v
                        per_param[name][s] = a
                self.opt_state[i] = per_param
        self.loss.reset(numpy.zeros((), numpy.float32))
        self.n_err.reset(numpy.zeros((), numpy.int32))
        self.epoch_acc.reset(numpy.zeros((3, 3), numpy.float32))
        # span serving: the loader hands whole class spans to this unit,
        # which scans over them in one dispatch (kills per-minibatch
        # Python/dispatch overhead — the reference paid it per kernel).
        # Auto-enable only (None); a builder's explicit False stands.
        if getattr(self.loader, "supports_span", False) \
                and self.loader.span_serving is None:
            self.loader.span_serving = True
        super(GradientDescent, self).initialize(device=device, **kwargs)
        for layer in self.opt_state.values():
            for slots in layer.values():
                for arr in slots.values():
                    arr.initialize(self.device)

    # -- pipeline parallelism (pp first-class at the trainer, r5) --------------

    def _make_pp_plan(self):
        """Locate the pipelineable TRUNK — the longest contiguous run
        of shape-preserving forwards with identical type/config/param
        shapes (e.g. the TransformerBlock × N stack) — and split it
        into ``pp`` stages.  SURVEY §2.3: every strategy a first-class
        mesh-axis config; pp mirrors sp's r4 treatment (an explicit
        communication schedule the trainer owns, param storage stays
        replicated like sp/dp)."""
        S = self.mesh.shape["pp"]
        for ax in ("tp", "fsdp", "sp", "ep"):
            if self.mesh.shape.get(ax, 1) > 1:
                raise ValueError(
                    "pp composes with dp only (got %s>1): shard the "
                    "trunk over pp×dp, or drop the pp axis" % ax)

        def signature(u):
            return (type(u).__name__, repr(sorted(
                u.export_config().items(), key=str)),
                tuple(sorted((n, a.mem.shape)
                             for n, a in u.param_arrays().items())))

        best = (0, 0)
        i = 0
        units = self.forwards
        while i < len(units):
            u = units[i]
            if isinstance(u, DropoutForward) \
                    or tuple(u.input.shape) != tuple(u.output.shape):
                i += 1
                continue
            j = i
            sig = signature(u)
            while j < len(units) and not isinstance(
                    units[j], DropoutForward) \
                    and tuple(units[j].input.shape) == tuple(
                        units[j].output.shape) \
                    and signature(units[j]) == sig:
                j += 1
            if j - i > best[1] - best[0]:
                best = (i, j)
            i = j
        start, end = best
        n = end - start
        if n < S or n % S:
            raise ValueError(
                "pp=%d needs a homogeneous shape-preserving trunk with "
                "a stage-divisible length; found %d matching units "
                "(forwards[%d:%d]) — use a layer count divisible by pp"
                % (S, n, start, end))
        n_micro = int(self.pp_microbatches or S)
        mb = self.loader.max_minibatch_size
        dp_total = self.mesh.shape.get("dp", 1)  # fsdp rejected above
        per_dev = mb // dp_total
        if mb % dp_total or per_dev % n_micro:
            raise ValueError(
                "minibatch %d must divide into dp extent %d and then "
                "into %d pp microbatches per dp slice"
                % (mb, dp_total, n_micro))
        batch_axes = ("dp",) if dp_total > 1 else ()
        return {"start": start, "end": end, "stages": S,
                "n_micro": n_micro, "batch_axes": batch_axes}

    def _pp_trunk_apply(self, params, h):
        """Stack the trunk units' params stage-major and run the GPipe
        schedule (parallel/pipeline.gpipe_train) inside the fused
        step — fwd, bwd (transposed ppermute schedule) and the solver
        update share one XLA program."""
        from veles_tpu.parallel.pipeline import gpipe_train
        plan = self._pp_plan_
        start, end, S = plan["start"], plan["end"], plan["stages"]
        trunk = self.forwards[start:end]
        k = len(trunk) // S
        stacked = {
            j: {name: jnp.stack(
                [params[start + s * k + j][name] for s in range(S)])
                for name in params[start]}
            for j in range(k)}
        unit0 = trunk[0]

        def stage_fn(stage_params, h):
            for j in range(k):
                p = stage_params[j]
                if getattr(unit0, "remat", False):
                    h = jax.checkpoint(unit0.apply)(p, h)
                else:
                    h = unit0.apply(p, h)
            return h

        return gpipe_train(self.mesh, stage_fn, stacked, h,
                           plan["n_micro"],
                           batch_axes=plan["batch_axes"])

    # -- the fused program -----------------------------------------------------

    def _forward(self, params, x, key, train):
        """Compose the chain; returns the trainer-facing head output
        (logits for a softmax head).  On a ``pp`` mesh the trunk runs
        the GPipe schedule; pre/post units run replicated."""
        h = x
        plan = self._pp_plan_
        i = 0
        while i < len(self.forwards):
            if plan is not None and i == plan["start"]:
                h = self._pp_trunk_apply(params, h)
                i = plan["end"]
                continue
            u = self.forwards[i]
            p = {name: params[i][name] for name in params[i]}
            if isinstance(u, DropoutForward):
                if train:
                    key, sub = jax.random.split(key)
                    h = u.apply_train(p, h, sub)
                else:
                    h = u.apply(p, h)
            elif isinstance(u, All2AllSoftmax) and i == len(
                    self.forwards) - 1:
                h = u.logits(p, h)
            elif getattr(u, "remat", False):
                # recompute this unit in the backward pass instead of
                # saving its internals (nn_units.ForwardBase.remat)
                h = jax.checkpoint(u.apply)(p, h)
            else:
                h = u.apply(p, h)
            i += 1
        return h

    def _target_of(self, labels, targets):
        return targets if isinstance(self.evaluator, EvaluatorMSE) \
            else labels

    def _make_minibatch_step(self):
        """The per-minibatch fused body shared by the single-step jit and
        the span scan: forward + loss + (cond) backward/solver + epoch
        accounting.

        Health (telemetry/health.py): the step also returns a 5-vector
        ``[grad_norm, weight_norm, update_ratio, nonfinite, loss]``
        computed IN-GRAPH (cheap jnp reductions over pytrees XLA fuses
        into the step) — the host reads one tiny array instead of
        re-walking the parameters.  Under the ``skip_step`` policy a
        non-finite update is dropped in the same program: parameters
        and solver state keep their pre-step values, and the
        epoch-accounting row contributes only its sample count (the
        epoch-completion gate still advances), so a single poisoned
        minibatch cannot contaminate the weights before the host even
        hears about it.  The policy knobs are baked at trace time;
        the dispatch sites rebuild the cached steps when they change
        (:meth:`_maybe_invalidate_steps`)."""
        from veles_tpu.telemetry.health import health_config
        hcfg = health_config()
        health_on = hcfg["enabled"]
        skip_nonfinite = health_on and hcfg["policy"] == "skip_step"
        solver = get_solver(self.solver_name)
        schedule = get_schedule(self.lr_schedule, **self.lr_schedule_params)
        hps = {i: {name: self._layer_hp(u, name)
                   for name in u.param_arrays()}
               for i, u in enumerate(self.forwards)}
        is_mse = isinstance(self.evaluator, EvaluatorMSE)

        augment_fn = None
        if self.augment is not None:
            if callable(self.augment):
                augment_fn = self.augment
            else:
                from veles_tpu.ops.augment import make_augment
                augment_fn = make_augment(**dict(self.augment))

        target_is_input = getattr(self.evaluator, "TARGET_IS_INPUT",
                                  False)

        def loss_and_metrics(params, x, target, size, key, train):
            if train and augment_fn is not None:
                key, sub = jax.random.split(key)
                x = augment_fn(x, sub)
            if target_is_input:
                # sequence objectives (EvaluatorNextToken) score the
                # model against its own input tokens
                target = x
            y = self._forward(params, x, key, train)
            loss = self.evaluator.loss(y, target, size)
            if hasattr(self.evaluator, "train_metrics"):
                n_err = self.evaluator.train_metrics(y, target, size)
            elif is_mse:
                n_err = jnp.zeros((), jnp.int32)
            else:
                # argmax over logits is valid for any softmax-CE head,
                # explicit All2AllSoftmax or plain logits layer alike
                pred = jnp.argmax(y, axis=-1).astype(jnp.int32)
                mask = jnp.arange(y.shape[0]) < size
                n_err = jnp.sum(jnp.where(
                    mask, (pred != target).astype(jnp.int32), 0))
            return loss, n_err

        def sq_norm(tree):
            leaves = jax.tree_util.tree_leaves(tree)
            total = jnp.zeros((), jnp.float32)
            for leaf in leaves:
                total = total + jnp.sum(
                    jnp.square(leaf.astype(jnp.float32)))
            return total

        def train_step(params, opt_state, acc, x, target, size, class_id,
                       step_no, lr_mult, key):
            def do_train(args):
                params, opt_state = args
                (loss, n_err), grads = jax.value_and_grad(
                    loss_and_metrics, has_aux=True)(
                        params, x, target, size, key, True)
                # lr_mult is traced so Rollback's lr changes don't
                # recompile the whole program
                scale = lr_mult * schedule(step_no)
                new_params, new_opt = {}, {}
                for i in params:
                    new_params[i], new_opt[i] = {}, {}
                    for name in params[i]:
                        hp = dict(hps[i][name])
                        hp["lr"] = hp["lr"] * scale
                        p, s = solver.update(
                            params[i][name], grads[i][name],
                            opt_state[i][name], hp)
                        new_params[i][name] = p
                        new_opt[i][name] = s
                if not health_on:
                    return (new_params, new_opt, loss, n_err,
                            jnp.zeros((5,), jnp.float32))
                grad_sq = sq_norm(grads)
                bad = jnp.where(
                    jnp.isfinite(loss) & jnp.isfinite(grad_sq),
                    jnp.float32(0), jnp.float32(1))
                if skip_nonfinite:
                    keep_old = bad > 0
                    new_params = jax.tree.map(
                        lambda new, old: jnp.where(keep_old, old, new),
                        new_params, params)
                    new_opt = jax.tree.map(
                        lambda new, old: jnp.where(keep_old, old, new),
                        new_opt, opt_state)
                weight_sq = sq_norm(new_params)
                update_sq = sq_norm(jax.tree.map(
                    lambda new, old: new.astype(jnp.float32)
                    - old.astype(jnp.float32), new_params, params))
                health = jnp.stack([
                    jnp.sqrt(grad_sq), jnp.sqrt(weight_sq),
                    jnp.sqrt(update_sq)
                    / (jnp.sqrt(weight_sq) + jnp.float32(1e-12)),
                    bad, loss.astype(jnp.float32)])
                return new_params, new_opt, loss, n_err, health

            def do_eval(args):
                params, opt_state = args
                loss, n_err = loss_and_metrics(
                    params, x, target, size, key, False)
                bad = jnp.where(jnp.isfinite(loss), jnp.float32(0),
                                jnp.float32(1)) if health_on \
                    else jnp.float32(0)
                health = jnp.stack([
                    jnp.float32(0), jnp.float32(0), jnp.float32(0),
                    bad, loss.astype(jnp.float32)])
                return params, opt_state, loss, n_err, health

            params, opt_state, loss, n_err, health = jax.lax.cond(
                class_id == TRAIN, do_train, do_eval,
                (params, opt_state))
            # per-class epoch accounting stays on device: one row of
            # [n_err, loss*size, size] added to the class's
            # accumulator.  The size row stays in SAMPLE units — the
            # DCN master's epoch-completion gate compares it against
            # class_lengths.  Sequence objectives (EvaluatorNextToken)
            # count errors per TOKEN, so their n_err scales down by
            # tokens-per-sample: the decision layer's error %% is then
            # the wrong-token percentage, and loss (already per token)
            # divided by samples stays the per-token CE.
            per_sample = 1
            if hasattr(self.evaluator, "metric_units"):
                per_sample = self.evaluator.metric_units(x)
            row = jnp.stack([n_err.astype(jnp.float32) / per_sample,
                             loss * size, size.astype(jnp.float32)])
            if skip_nonfinite:
                # a skipped TRAIN step keeps its NaN loss/err out of
                # the epoch accumulator but must still contribute its
                # SIZE: the DCN master closes epochs when acc[cls][2]
                # reaches the class lengths (decision.py), so zeroing
                # the sample count would hang the distributed epoch.
                # Eval steps are never skipped — their row stays
                # intact regardless of loss finiteness (under
                # warn/halt the poison stays visible on purpose).
                skipped = (health[3] > 0) & (class_id == TRAIN)
                row = jnp.where(
                    skipped,
                    jnp.stack([jnp.float32(0), jnp.float32(0),
                               size.astype(jnp.float32)]),
                    row)
            onehot = (jnp.arange(3) == class_id).astype(jnp.float32)
            acc = acc + onehot[:, None] * row[None, :]
            return params, opt_state, acc, loss, n_err, health

        return train_step

    def _build_train_step(self):
        from veles_tpu.telemetry import track_jit
        train_step = self._make_minibatch_step()
        if self.mesh is None:
            return track_jit(
                "trainer.minibatch_step",
                jax.jit(train_step, donate_argnums=(0, 1, 2)))
        params_sh, opt_sh, x_sh, tgt_sh, rep = self._ensure_shardings()
        return track_jit("trainer.minibatch_step", jax.jit(
            train_step,
            in_shardings=(params_sh, opt_sh, rep, x_sh, tgt_sh,
                          rep, rep, rep, rep, rep),
            out_shardings=(params_sh, opt_sh, rep, rep, rep, rep),
            donate_argnums=(0, 1, 2)))

    def _build_span_step(self):
        """One jitted dispatch per class span: ``lax.scan`` over the
        loader's index schedule, gathering each minibatch from the
        HBM-resident dataset in-graph (north star: the whole accelerated
        segment is one XLA program per run)."""
        minibatch_step = self._make_minibatch_step()

        def span_step(params, opt_state, acc, ds, tgt_ds, idx, sizes,
                      class_id, step0, lr_mult, base_key):
            def body(carry, xs):
                params, opt_state, acc, k = carry
                idx_k, size_k = xs
                x = jnp.take(ds, idx_k, axis=0, mode="clip")
                tgt = jnp.take(tgt_ds, idx_k, axis=0, mode="clip")
                key = jax.random.fold_in(base_key, k)
                (params, opt_state, acc, loss, n_err,
                 health) = minibatch_step(
                    params, opt_state, acc, x, tgt, size_k, class_id,
                    step0 + k.astype(jnp.float32), lr_mult, key)
                return (params, opt_state, acc, k + 1), (loss, n_err,
                                                         health)

            (params, opt_state, acc, _), (losses, n_errs,
                                          healths) = jax.lax.scan(
                body, (params, opt_state, acc, jnp.int32(0)), (idx, sizes))
            # health over the span: last step's norms/loss, nonfinite
            # steps SUMMED so a single poisoned minibatch mid-span is
            # still counted at the boundary read
            health = jnp.concatenate([
                healths[-1, :3], jnp.sum(healths[:, 3])[None],
                healths[-1, 4:]])
            return params, opt_state, acc, losses[-1], n_errs[-1], health

        from veles_tpu.telemetry import track_jit
        if self.mesh is None:
            return track_jit(
                "trainer.span_step",
                jax.jit(span_step, donate_argnums=(0, 1, 2)))
        from jax.sharding import NamedSharding, PartitionSpec as P
        params_sh, opt_sh, x_sh, tgt_sh, rep = self._ensure_shardings()
        batch_axes = x_sh.spec[0] if len(x_sh.spec) else None
        idx_sh = NamedSharding(self.mesh, P(None, batch_axes))
        self._idx_sharding_ = idx_sh  # _run_span pre-places host indices
        sizes_sh = rep
        return track_jit("trainer.span_step", jax.jit(
            span_step,
            in_shardings=(params_sh, opt_sh, rep, rep, rep, idx_sh,
                          sizes_sh, rep, rep, rep, rep),
            out_shardings=(params_sh, opt_sh, rep, rep, rep, rep),
            donate_argnums=(0, 1, 2)))

    def _ensure_shardings(self):
        """NamedShardings over self.mesh — XLA then inserts the gradient
        psum over dp and the tp collectives on ICI."""
        if self._shardings_ is not None:
            return self._shardings_
        from veles_tpu.parallel import sharding as shlib
        mesh = self.mesh
        params_sh = {
            i: {name: shlib.param_sharding(mesh, name, arr.mem.shape)
                for name, arr in u.param_arrays().items()}
            for i, u in enumerate(self.forwards)}
        opt_sh = {
            i: {name: {s: params_sh[i][name]
                       for s in self.opt_state[i][name]}
                for name in self.opt_state[i]}
            for i in self.opt_state}
        # Adam's step counter is a scalar — replicate it
        for i, layer in self.opt_state.items():
            for name, slots in layer.items():
                for s, arr in slots.items():
                    if len(arr.shape) == 0:  # dev-born slots have no mem
                        opt_sh[i][name][s] = shlib.replicated(mesh)
        mb = self.loader.max_minibatch_size
        x_shape = self.loader.minibatch_data.shape
        # dim 1 of the DATA minibatch is a sequence dim ONLY when the
        # FIRST forward consumes it as one (SEQ_DIM1_INPUT on the unit
        # class — attention/transformer/embedding/recurrent); image
        # workflows' dim 1 is height and must not sp-shard, even if a
        # sequence unit appears later in the chain (ADVICE.md r4 #2)
        has_seq = bool(self.forwards) and getattr(
            self.forwards[0], "SEQ_DIM1_INPUT", False)
        x_sh = shlib.batch_sharding(
            mesh, len(x_shape), dim0=mb,
            seq_dim1=x_shape[1]
            if has_seq and len(x_shape) >= 2 else None)
        tgt_ndim = len(self.loader.minibatch_targets.shape) \
            if isinstance(self.evaluator, EvaluatorMSE) \
            else len(self.loader.minibatch_labels.shape)
        tgt_sh = shlib.batch_sharding(mesh, tgt_ndim, dim0=mb)
        rep = shlib.replicated(mesh)
        self._shardings_ = (params_sh, opt_sh, x_sh, tgt_sh, rep)
        return self._shardings_

    # -- execution -------------------------------------------------------------

    def _gather_state(self):
        # the step DONATES params/opt_state (donate_argnums=(0, 1)) —
        # donatable_devmem detaches buffers whose host mirror shares
        # the allocation (XLA:CPU zero-copy device_put / map_read
        # views), the span-step heap-corruption fix (ROUND6_NOTES.md)
        params = {i: {name: arr.donatable_devmem()
                      for name, arr in u.param_arrays().items()}
                  for i, u in enumerate(self.forwards)}
        opt_state = {i: {name: {s: arr.donatable_devmem()
                                for s, arr in slots.items()}
                         for name, slots in layer.items()}
                     for i, layer in self.opt_state.items()}
        return params, opt_state

    def _adopt_state(self, new_params, new_opt):
        for i, u in enumerate(self.forwards):
            for name, arr in u.param_arrays().items():
                arr.devmem = new_params[i][name]
        for i, layer in self.opt_state.items():
            for name, slots in layer.items():
                for s, arr in slots.items():
                    arr.devmem = new_opt[i][name][s]

    def _mesh_prepare(self, params, opt_state):
        """Re-distribute state pytrees onto the mesh when a host-side
        write (rollback, snapshot resume) reset a leaf to single-device
        placement — one leaf check suffices since all leaves travel
        together; normally state adopts the sharded step outputs."""
        from veles_tpu.parallel import sharding as shlib
        params_sh, opt_sh, _, _, rep = self._shardings_
        if self.epoch_acc.devmem.sharding != rep:
            self.epoch_acc.devmem = shlib.put(self.epoch_acc.devmem, rep)
        i0 = next(iter(params))
        n0 = next(iter(params[i0]))
        if params[i0][n0].sharding != params_sh[i0][n0]:
            params = jax.tree.map(shlib.put, params, params_sh)
            opt_state = jax.tree.map(shlib.put, opt_state, opt_sh)
        return params, opt_state

    def _maybe_invalidate_steps(self):
        """health.py promises config is read per call, but the
        in-graph skip guard is baked into the step at trace time —
        rebuild the cached jitted steps when the effective
        (enabled, skip_step) pair changes so tests and ``-c``
        overrides of ``root.common.health.*`` keep applying after
        the first dispatch (one recompile, not silence)."""
        from veles_tpu.telemetry.health import health_config
        hcfg = health_config()
        sig = (hcfg["enabled"],
               hcfg["enabled"] and hcfg["policy"] == "skip_step")
        if getattr(self, "_health_sig_", sig) != sig:
            self._train_step_ = None
            self._span_step_ = None
        self._health_sig_ = sig

    def run(self):
        l = self.loader
        if getattr(l, "span_fresh_", False):
            self._run_span()
            return
        self._maybe_invalidate_steps()
        if self._train_step_ is None:
            self._train_step_ = self._build_train_step()
        params, opt_state = self._gather_state()
        # under the asynchronous input pipeline these devmem reads are
        # already-on-device batch handles installed at pop time
        # (loader/prefetch.py) — no synchronous host→HBM upload here
        x = l.minibatch_data.devmem
        labels = l.minibatch_labels.devmem
        targets = getattr(l, "minibatch_targets", None)
        is_mse = isinstance(self.evaluator, EvaluatorMSE)
        target = targets.devmem if is_mse else labels
        if self._shardings_ is not None:
            from veles_tpu.parallel import sharding as shlib
            _, _, x_sh, tgt_sh, _ = self._shardings_
            pf = getattr(l, "prefetch_", None)
            if pf not in (None, False) \
                    and not shlib.is_cross_process(x_sh):
                # teach the uploader thread the step's input shardings
                # so the put below becomes a no-op re-place
                pf.set_placement(
                    x_sh,
                    labels_sharding=None if is_mse else tgt_sh,
                    targets_sharding=tgt_sh if is_mse else None)
            if shlib.is_cross_process(x_sh):
                # feed the host mirror directly: putting the local device
                # buffer would download it again just to re-assemble
                x = l.minibatch_data.map_read().mem
                target = (l.minibatch_targets if isinstance(
                    self.evaluator, EvaluatorMSE)
                    else l.minibatch_labels).map_read().mem
            x = shlib.put(x, x_sh)
            target = shlib.put(target, tgt_sh)
            params, opt_state = self._mesh_prepare(params, opt_state)
        key = self.prng.peek_key(self.global_step)
        new_params, new_opt, acc, loss, n_err, health = \
            self._train_step_(
                params, opt_state, self.epoch_acc.donatable_devmem(),
                x, target,
                jnp.int32(l.minibatch_size),
                jnp.int32(l.minibatch_class),
                jnp.float32(self.global_step),
                jnp.float32(self.lr_multiplier), key)
        self.epoch_acc.devmem = acc
        self._adopt_state(new_params, new_opt)
        self.loss.devmem = loss
        self.n_err.devmem = n_err
        if l.minibatch_class == TRAIN:
            self.global_step += 1
            self._observe_health(health)

    def _run_span(self):
        """Consume a whole class span in ONE dispatch (lax.scan inside
        jit over the loader's index schedule)."""
        l = self.loader
        l.span_fresh_ = False
        self._maybe_invalidate_steps()
        if self._span_step_ is None:
            self._span_step_ = self._build_span_step()
        params, opt_state = self._gather_state()
        is_mse = isinstance(self.evaluator, EvaluatorMSE)
        ds = l.dataset_dev
        tgt = l.targets_dev if is_mse else l.labels_dev
        if self._shardings_ is not None or self.mesh is not None:
            _, _, _, _, rep = self._ensure_shardings()
            if ds.sharding != rep:
                # re-home the loader's dataset onto the mesh (replicated,
                # like each reference slave holding a full copy) — the
                # single-device original is released, not duplicated
                l.rehome_dataset(rep)
                ds = l.dataset_dev
                tgt = l.targets_dev if is_mse else l.labels_dev
            params, opt_state = self._mesh_prepare(params, opt_state)
        idx = l.span_indices_
        if getattr(self, "_idx_sharding_", None) is not None:
            # multi-process meshes reject numpy args with non-trivial
            # shardings — assemble the global index array explicitly
            from veles_tpu.parallel import sharding as shlib
            idx = shlib.put(idx, self._idx_sharding_)
        key = self.prng.peek_key(self.global_step)
        new_params, new_opt, acc, loss, n_err, health = \
            self._span_step_(
                params, opt_state, self.epoch_acc.donatable_devmem(),
                ds, tgt,
                idx, l.span_sizes_,
                jnp.int32(l.span_class_), jnp.float32(self.global_step),
                jnp.float32(self.lr_multiplier), key)
        self.epoch_acc.devmem = acc
        self._adopt_state(new_params, new_opt)
        self.loss.devmem = loss
        self.n_err.devmem = n_err
        if l.span_class_ == TRAIN:
            self.global_step += len(l.span_sizes_)
            self._observe_health(health, force=True)

    def _observe_health(self, health, force=False):
        """Feed the jitted step's health vector to the process-wide
        monitor — ONE small device→host read per observed dispatch,
        decimated by ``root.common.health.sync_every`` on the
        per-minibatch path (a span boundary always syncs: it is
        already a host touchpoint).  Acts on the policy verdict: halt
        stops the workflow gracefully instead of crashing."""
        from veles_tpu.telemetry import health as health_lib
        cfg = health_lib.health_config()
        if not cfg["enabled"]:
            return
        self._health_ticks_ = getattr(self, "_health_ticks_", 0) + 1
        every = max(int(cfg["sync_every"]), 1)
        if not force and self._health_ticks_ % every:
            return
        vals = numpy.asarray(health)
        action = health_lib.monitor.on_train_step(
            grad_norm=float(vals[0]), weight_norm=float(vals[1]),
            update_ratio=float(vals[2]), nonfinite=float(vals[3]),
            loss=float(vals[4]), unit=self.name)
        if action == "halt":
            self.error(
                "health policy 'halt': non-finite training step - "
                "stopping the workflow (process stays up; see "
                "GET /healthz and the flight recorder)")
            if self._workflow is not None:
                self._workflow.on_workflow_finished()

    # -- elastic DCN sync (parameter-server semantics over the
    #    coordinator, ref: the Znicz GD units' weight-delta exchange the
    #    reference routed through workflow.py:478-558) ---------------------------

    negotiates_on_connect = True

    def _read_params_numpy(self):
        out = {}
        for i, u in enumerate(self.forwards):
            out[i] = {}
            for name, arr in u.param_arrays().items():
                arr.map_read()
                out[i][name] = numpy.array(arr.mem)
        return out

    def generate_data_for_slave(self, slave=None):
        """Master → worker: the job carries the current parameters."""
        return {"params": self._read_params_numpy()}

    def apply_data_from_master(self, data):
        """Worker: install the master's parameters and remember them as
        the delta baseline for this job."""
        params = data["params"]
        for i, u in enumerate(self.forwards):
            for name, arr in u.param_arrays().items():
                arr.map_invalidate()
                arr.mem[...] = params[i][name]
                arr.unmap()
        self._job_params_ = params

    def generate_data_for_master(self):
        """Worker → master: parameter deltas (async-SGD update) + the
        epoch accounting accumulated on this worker since the last send."""
        now = self._read_params_numpy()
        base = getattr(self, "_job_params_", None) or now
        delta = {i: {name: now[i][name] - base[i][name]
                     for name in now[i]} for i in now}
        acc = self.read_epoch_acc(reset_classes=(0, 1, 2), as_array=True)
        return {"delta": delta, "acc": acc}

    def apply_data_from_slave(self, data, slave=None):
        """Master: merge the worker's delta into the live parameters and
        fold its epoch accounting into the (float64) master
        accumulator."""
        for i, u in enumerate(self.forwards):
            for name, arr in u.param_arrays().items():
                arr.map_write()
                arr.mem[...] += data["delta"][i][name]
                arr.unmap()
        self._master_acc_ += numpy.asarray(data["acc"], numpy.float64)

    def drop_slave(self, slave=None):
        pass  # in-flight deltas from a dead worker are simply lost

    def read_epoch_acc(self, reset_classes=(), as_array=False):
        """One host sync: {class: (n_err, loss_sum, samples)}; resets the
        requested class rows for the next epoch."""
        if self.is_master:
            # the master's graph never runs; its accounting lives in the
            # float64 host accumulator fed by apply_data_from_slave
            acc = numpy.array(self._master_acc_)
            for c in reset_classes:
                self._master_acc_[c] = 0
        else:
            self.epoch_acc.map_read()
            acc = numpy.array(self.epoch_acc.mem)
            if len(reset_classes):
                self.epoch_acc.map_write()
                for c in reset_classes:
                    self.epoch_acc.mem[c] = 0
                self.epoch_acc.unmap()
        if as_array:
            return acc
        return {c: (float(acc[c, 0]), float(acc[c, 1]), float(acc[c, 2]))
                for c in range(3)}

    def step(self, **tensors):
        raise RuntimeError("GradientDescent dispatches its own program")
