"""Autoregressive generation from a trained next-token LM.

No reference analogue (the reference had no sequence models at all —
SURVEY.md §5); this completes the LM loop the r5 stack opened:
train (``samples/lm.py``) → snapshot → :func:`generate`.

The whole decode is ONE jitted program: a ``lax.scan`` over decode
steps on a fixed-length token buffer.  Causal attention makes the
fixed buffer exact — positions past the cursor are *future* positions
to every already-generated token, so they cannot influence the logits
the sampler reads (the buffer's tail holds zeros, not padding that
would need masking).  Each step runs the full forward over the buffer
(O(L²) per step without a KV cache — exactness first; a cached decode
is a layout change inside TransformerBlock, not an API change).
"""

import functools

import jax
import jax.numpy as jnp


def _chain_logits(forwards, params, tokens):
    h = tokens
    for i, u in enumerate(forwards):
        h = u.apply(params[i], h)
    return h


def generate(forwards, prompt, steps, temperature=0.0, top_k=0,
             key=None):
    """Decode ``steps`` tokens after ``prompt`` [batch, prompt_len]
    (int32) through a forward chain ending in per-token logits
    (Embedding → TransformerBlock × N → TokenProjection).

    - ``temperature`` 0 → greedy argmax; otherwise logits/temperature
      categorical sampling (``key`` required);
    - ``top_k`` > 0 restricts sampling to the k most likely tokens.

    Returns [batch, prompt_len + steps] tokens."""
    params = {i: {name: jnp.asarray(arr.map_read().mem)
                  for name, arr in u.param_arrays().items()}
              for i, u in enumerate(forwards)}
    prompt = jnp.asarray(prompt, jnp.int32)
    b, p_len = prompt.shape
    total = p_len + int(steps)
    if temperature and key is None:
        raise ValueError("sampling (temperature > 0) needs a PRNG key")
    if key is None:
        key = jax.random.key(0)
    for u in forwards:
        pos_table = getattr(u, "positions", None)
        if pos_table is not None and hasattr(pos_table, "shape") \
                and len(pos_table.shape) == 2 \
                and total > pos_table.shape[0]:
            raise ValueError(
                "prompt_len + steps = %d exceeds the model's learned "
                "positional table (%d — the training sequence length)"
                % (total, pos_table.shape[0]))
    vocab = getattr(forwards[-1], "vocab", None)
    if top_k and vocab is not None and int(top_k) > int(vocab):
        raise ValueError("top_k %d > vocab %d" % (top_k, vocab))
    if top_k and not temperature:
        raise ValueError(
            "top_k only applies to sampling — set temperature > 0 "
            "(greedy ignores it)")

    buf0 = jnp.zeros((b, total), jnp.int32)
    buf0 = jax.lax.dynamic_update_slice(buf0, prompt, (0, 0))

    def sample(logits, k):
        if temperature:
            z = logits / float(temperature)
            if top_k:
                kth = jnp.sort(z, axis=-1)[:, -int(top_k)][:, None]
                z = jnp.where(z < kth, -jnp.inf, z)
            return jax.random.categorical(k, z).astype(jnp.int32)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def step(params, carry, _):
        buf, pos, k = carry
        logits = _chain_logits(forwards, params, buf)
        # logits at the cursor's predecessor predict the cursor token
        row = jax.lax.dynamic_slice(
            logits, (0, pos - 1, 0), (b, 1, logits.shape[-1]))[:, 0]
        k, sub = jax.random.split(k)
        nxt = sample(row, sub)
        buf = jax.lax.dynamic_update_slice(buf, nxt[:, None], (0, pos))
        return (buf, pos + 1, k), None

    # params travel as jit ARGUMENTS (constants baked into the trace
    # would bloat the executable) and the compiled decode is cached on
    # the chain's ARCHITECTURE SIGNATURE + every static piece of the
    # decode config (batch, lengths, sampler settings — they are
    # baked into the step closure).  Identical signatures define the
    # identical computation, so sharing the executable across chains
    # is correct — and object ids would be unsound (id reuse after gc
    # replayed a stale chain's executable; caught by the test suite)
    sig = tuple(
        (type(u).__name__,
         repr(sorted(u.export_config().items(), key=str)),
         tuple(sorted((n, tuple(a.mem.shape))
                      for n, a in u.param_arrays().items())))
        for u in forwards)
    cache_key = (sig, b, int(steps), p_len,
                 float(temperature or 0.0), int(top_k or 0))
    decode = _decode_cached(cache_key, _StepClosure(step))
    return decode(params, buf0, key)


class _StepClosure:
    """Always-equal wrapper: the cache keys on ``cache_key`` (the
    architecture signature + batch/lengths/sampler settings) —
    everything the step closure actually varies over — while the
    closure itself rides along uncompared."""

    def __init__(self, fn):
        self.fn = fn

    def __hash__(self):
        return 0

    def __eq__(self, other):
        return isinstance(other, _StepClosure)


@functools.lru_cache(maxsize=16)
def _decode_cached(cache_key, step_closure):
    steps, p_len = cache_key[2], cache_key[3]

    @jax.jit
    def decode(params, buf, key):
        (buf, _, _), _ = jax.lax.scan(
            functools.partial(step_closure.fn, params),
            (buf, jnp.int32(p_len), key), None, length=steps)
        return buf

    return decode
