"""Autoregressive generation from a trained next-token LM.

No reference analogue (the reference had no sequence models at all —
SURVEY.md §5); this completes the LM loop the r5 stack opened:
train (``samples/lm.py``) → snapshot → :func:`generate`.

The whole decode is ONE jitted program: a ``lax.scan`` over decode
steps on a fixed-length token buffer.  Causal attention makes the
fixed buffer exact — positions past the cursor are *future* positions
to every already-generated token, so they cannot influence the logits
the sampler reads (the buffer's tail holds zeros, not padding that
would need masking).  Each step runs the full forward over the buffer
(O(L²) per step without a KV cache — exactness first; a cached decode
is a layout change inside TransformerBlock, not an API change).
"""

import functools

import jax
import jax.numpy as jnp
import numpy

from veles_tpu.telemetry import track_jit


def _chain_logits(forwards, params, tokens):
    h = tokens
    for i, u in enumerate(forwards):
        h = u.apply(params[i], h)
    return h


def _chain_step(forwards, params, tok, pos, caches):
    """One-token forward with per-block KV caches: tok [batch, 1] ids
    at sequence index ``pos`` → ([batch, 1, vocab] logits, caches')."""
    h = tok
    out = dict(caches)
    for i, u in enumerate(forwards):
        if hasattr(u, "init_cache"):
            h, out[i] = u.apply_step(params[i], h, pos, caches[i])
        elif hasattr(u, "apply_step"):
            h = u.apply_step(params[i], h, pos)
        else:
            h = u.apply(params[i], h)
    return h, out


def _device_params(forwards):
    # device-resident params (Array.devmem uploads lazily ONCE and
    # stays coherent): repeated decode calls must not re-ship the
    # weights host→device — through a remote-device tunnel that upload
    # dwarfs the decode itself
    return {i: {name: arr.devmem
                for name, arr in u.param_arrays().items()}
            for i, u in enumerate(forwards)}


def _check_positions(forwards, total):
    for u in forwards:
        pos_table = getattr(u, "positions", None)
        if pos_table is not None and hasattr(pos_table, "shape") \
                and len(pos_table.shape) == 2 \
                and total > pos_table.shape[0]:
            raise ValueError(
                "prompt_len + steps = %d exceeds the model's learned "
                "positional table (%d — the training sequence length)"
                % (total, pos_table.shape[0]))


def _arch_sig(forwards):
    # the architecture signature the compiled-decode caches key on
    # (identical signatures define the identical computation, so
    # sharing the executable across chains is correct — and object ids
    # would be unsound: id reuse after gc replayed a stale chain's
    # executable; caught by the test suite)
    return tuple(
        (type(u).__name__,
         repr(sorted(u.export_config().items(), key=str)),
         tuple(sorted((n, tuple(a.mem.shape))
                      for n, a in u.param_arrays().items())))
        for u in forwards)


def _make_pre_step(forwards, b):
    """Prompt-prefill step builder: consume one prompt token at
    ``pos``, populate the KV caches, sample nothing."""
    def pre_step(params, carry, _):
        buf, pos, caches = carry
        tok = jax.lax.dynamic_slice(buf, (0, pos), (b, 1))
        _, caches = _chain_step(forwards, params, tok, pos, caches)
        return (buf, pos + 1, caches), None
    return pre_step


def _make_prefill(forwards):
    """BATCHED prompt-prefill builder (serving PR): ONE forward pass
    over the whole prompt fills every cacheable block's K/V rows —
    TTFT drops from O(prompt_len) compiled scan steps to O(1).  The
    chain runs only up to the LAST cacheable block (later units fill
    no caches and their prompt outputs are discarded).  Returns None
    when any cacheable unit predates ``apply_prefill`` — the caller
    falls back to the per-token scan."""
    cacheable = [i for i, u in enumerate(forwards)
                 if hasattr(u, "init_cache")]
    if not cacheable or any(
            not hasattr(forwards[i], "apply_prefill")
            for i in cacheable):
        return None
    last = cacheable[-1]

    def prefill(params, toks, caches):
        h = toks
        out = dict(caches)
        for i, u in enumerate(forwards[:last + 1]):
            if hasattr(u, "init_cache"):
                h, out[i] = u.apply_prefill(params[i], h, caches[i])
            else:
                h = u.apply(params[i], h)
        return out
    return prefill


def kv_cache_eligible(forwards):
    """True when :func:`generate` can decode this chain with
    ``kv_cache=True``: every cacheable block is causal and every other
    unit either has a single-token step or is position-wise (the same
    predicate the kv path validates with)."""
    for u in forwards:
        if hasattr(u, "init_cache"):
            if not u.causal:
                return False
        elif not hasattr(u, "apply_step") \
                and not getattr(u, "DECODE_POINTWISE", False):
            return False
    return True


def generate(forwards, prompt, steps, temperature=0.0, top_k=0,
             key=None, kv_cache=False, prompt_lens=None,
             stop_token=None):
    """Decode ``steps`` tokens after ``prompt`` [batch, prompt_len]
    (int32) through a forward chain ending in per-token logits
    (Embedding → TransformerBlock × N → TokenProjection).

    - ``temperature`` 0 → greedy argmax; otherwise logits/temperature
      categorical sampling (``key`` required);
    - ``top_k`` > 0 restricts sampling to the k most likely tokens;
    - ``kv_cache`` True → single-token decode steps against per-block
      K/V caches (O(total) per token instead of O(total²) — the
      layout change the module docstring promises).  Exact for causal
      chains; greedy parity with the uncached scan is tested
      token-for-token in f32.  The sampling key schedule matches the
      uncached path (one split per decode step), so a given
      ``key``/settings pair draws the same tokens either way;
    - ``prompt_lens`` (optional, [batch] ints) — VARIABLE-LENGTH
      batched prompts: row ``n``'s prompt occupies its first
      ``prompt_lens[n]`` positions (front-aligned; pad the rest of the
      [batch, prompt_len] array arbitrarily — generation overwrites
      the padding in place as it reaches it) and its generated region
      starts right after.  Every row decodes to the shared buffer end
      ``prompt_len + steps``, so row ``n`` gets
      ``prompt_len + steps - prompt_lens[n]`` ≥ ``steps`` new tokens;
      slice ``out[n, :prompt_lens[n] + k]`` for exactly ``k``.
      Greedy per-row results equal a single-row decode of the same
      prompt (tested).  The lens ride the compiled decode as a traced
      argument — one executable serves ANY length mix at the same
      (batch, prompt_len, steps).  Key schedule: one split per buffer
      position (all rows advance in lockstep), so sampled streams
      differ from the uniform-length path's;
    - ``stop_token`` (optional int) — a row that GENERATES this token
      freezes: every later position repeats it (the shapes stay
      static; trim at the first occurrence).  Prompt occurrences do
      not stop a row — only generated ones count.

    Returns [batch, prompt_len + steps] tokens."""
    params = _device_params(forwards)
    prompt = jnp.asarray(prompt, jnp.int32)
    b, p_len = prompt.shape
    total = p_len + int(steps)
    lens = None
    if prompt_lens is not None:
        lens_np = numpy.asarray(prompt_lens, numpy.int32)
        if lens_np.shape != (b,):
            raise ValueError("prompt_lens must be [batch] ints")
        if lens_np.min() < 1 or lens_np.max() > p_len:
            raise ValueError(
                "prompt_lens must be in [1, %d] (the prompt width)"
                % p_len)
        lens = jnp.asarray(lens_np)
    if temperature and key is None:
        raise ValueError("sampling (temperature > 0) needs a PRNG key")
    if key is None:
        key = jax.random.key(0)
    _check_positions(forwards, total)
    vocab = getattr(forwards[-1], "vocab", None)
    if top_k and vocab is not None and int(top_k) > int(vocab):
        raise ValueError("top_k %d > vocab %d" % (top_k, vocab))
    if top_k and not temperature:
        raise ValueError(
            "top_k only applies to sampling — set temperature > 0 "
            "(greedy ignores it)")

    buf0 = jnp.zeros((b, total), jnp.int32)
    buf0 = jax.lax.dynamic_update_slice(buf0, prompt, (0, 0))

    def sample(logits, k):
        if temperature:
            z = logits / float(temperature)
            if top_k:
                kth = jnp.sort(z, axis=-1)[:, -int(top_k)][:, None]
                z = jnp.where(z < kth, -jnp.inf, z)
            return jax.random.categorical(k, z).astype(jnp.int32)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    # stop PRESENCE is static (no freeze ops compiled when absent);
    # the stop VALUE rides the carry as a traced scalar, so every
    # stop id shares one executable — same design as prompt_lens
    use_stop = stop_token is not None
    stop0 = jnp.int32(int(stop_token) if use_stop else -1)

    def freeze(nxt, consumed, consumed_pos, gen_start, stop_val):
        # a row whose last GENERATED token was the stop token repeats
        # it forever (consumed_pos >= gen_start ⇔ the consumed token
        # was generated, so prompt occurrences never freeze a row)
        if not use_stop:
            return nxt
        frozen = (consumed == stop_val) & (consumed_pos >= gen_start)
        return jnp.where(frozen, stop_val, nxt)

    def step(params, carry, _):
        buf, pos, k, stop_val = carry
        logits = _chain_logits(forwards, params, buf)
        # logits at the cursor's predecessor predict the cursor token
        row = jax.lax.dynamic_slice(
            logits, (0, pos - 1, 0), (b, 1, logits.shape[-1]))[:, 0]
        k, sub = jax.random.split(k)
        nxt = sample(row, sub)
        consumed = jax.lax.dynamic_slice(
            buf, (0, pos - 1), (b, 1))[:, 0]
        nxt = freeze(nxt, consumed, pos - 1, p_len, stop_val)
        buf = jax.lax.dynamic_update_slice(buf, nxt[:, None], (0, pos))
        return (buf, pos + 1, k, stop_val), None

    pre_step = _make_pre_step(forwards, b)

    def dec_step(params, carry, _):
        buf, pos, k, caches, stop_val = carry
        tok = jax.lax.dynamic_slice(buf, (0, pos), (b, 1))
        logits, caches = _chain_step(forwards, params, tok, pos, caches)
        k, sub = jax.random.split(k)
        nxt = sample(logits[:, 0], sub)
        nxt = freeze(nxt, tok[:, 0], pos, p_len, stop_val)
        buf = jax.lax.dynamic_update_slice(buf, nxt[:, None],
                                           (0, pos + 1))
        return (buf, pos + 1, k, caches, stop_val), None

    def var_step(params, carry, _):
        # variable-length lockstep (kv): consume position pos, write
        # pos+1 only for rows whose prompt has ended — prompt tokens
        # pass through untouched, padding is overwritten in place
        buf, pos, k, caches, row_lens, stop_val = carry
        tok = jax.lax.dynamic_slice(buf, (0, pos), (b, 1))
        logits, caches = _chain_step(forwards, params, tok, pos, caches)
        k, sub = jax.random.split(k)
        nxt = sample(logits[:, 0], sub)
        nxt = freeze(nxt, tok[:, 0], pos, row_lens, stop_val)
        cur = jax.lax.dynamic_slice(buf, (0, pos + 1), (b, 1))[:, 0]
        write = jnp.where(pos + 1 >= row_lens, nxt, cur)
        buf = jax.lax.dynamic_update_slice(buf, write[:, None],
                                           (0, pos + 1))
        return (buf, pos + 1, k, caches, row_lens, stop_val), None

    def var_step_full(params, carry, _):
        # variable-length lockstep, full-buffer rescan variant
        buf, pos, k, row_lens, stop_val = carry
        logits = _chain_logits(forwards, params, buf)
        row = jax.lax.dynamic_slice(
            logits, (0, pos, 0), (b, 1, logits.shape[-1]))[:, 0]
        k, sub = jax.random.split(k)
        nxt = sample(row, sub)
        consumed = jax.lax.dynamic_slice(buf, (0, pos), (b, 1))[:, 0]
        nxt = freeze(nxt, consumed, pos, row_lens, stop_val)
        cur = jax.lax.dynamic_slice(buf, (0, pos + 1), (b, 1))[:, 0]
        write = jnp.where(pos + 1 >= row_lens, nxt, cur)
        buf = jax.lax.dynamic_update_slice(buf, write[:, None],
                                           (0, pos + 1))
        return (buf, pos + 1, k, row_lens, stop_val), None

    # params travel as jit ARGUMENTS (constants baked into the trace
    # would bloat the executable) and the compiled decode is cached on
    # the chain's ARCHITECTURE SIGNATURE (_arch_sig) + every static
    # piece of the decode config (batch, lengths, sampler settings —
    # they are baked into the step closure)
    from veles_tpu import dtypes
    sig = _arch_sig(forwards)
    # the compute/precision policy is read from GLOBAL config inside
    # the trace (the casts are baked into the executable) — it must
    # key the cache or a dtype toggle would replay the other policy's
    # program on shape-identical calls
    cache_key = (sig, b, int(steps), p_len,
                 float(temperature or 0.0), int(top_k or 0),
                 bool(kv_cache), lens is not None, use_stop,
                 str(dtypes.compute_dtype()),
                 str(dtypes.matmul_precision()))
    if kv_cache:
        for u in forwards:
            if hasattr(u, "init_cache"):
                if not u.causal:
                    raise ValueError(
                        "kv_cache decoding needs causal blocks — a "
                        "non-causal block's past outputs change when "
                        "future tokens arrive, so single-token steps "
                        "cannot reproduce them")
            elif not hasattr(u, "apply_step") \
                    and not getattr(u, "DECODE_POINTWISE", False):
                # a sequence-mixing unit without a single-token step
                # (MultiHeadAttention, RNN/LSTM, pooling heads) would
                # silently attend/recur over ONE position — refuse
                # rather than decode garbage
                raise ValueError(
                    "kv_cache decoding: %s has no apply_step and is "
                    "not position-wise — use kv_cache=False for this "
                    "chain" % type(u).__name__)
        caches0 = {i: u.init_cache(b, total, dtypes.compute_dtype())
                   for i, u in enumerate(forwards)
                   if hasattr(u, "init_cache")}
        if lens is not None:
            decode = _decode_cached_kv_varlen(
                cache_key, _StepClosure(var_step))
            return decode(params, buf0, key, caches0, lens,
                          stop0)
        decode = _decode_cached_kv(
            cache_key, _StepClosure((_make_prefill(forwards),
                                     pre_step, dec_step)))
        return decode(params, buf0, key, caches0, stop0)
    if lens is not None:
        # positions before every row's prompt end need no forward at
        # all on the rescan path — start at the host-known min length
        # (part of the key: the scan length is baked into the trace)
        vmin = int(lens_np.min())
        decode = _decode_cached_varlen(
            cache_key + (vmin,), _StepClosure(var_step_full))
        return decode(params, buf0, key, lens, stop0)
    decode = _decode_cached(cache_key, _StepClosure(step))
    return decode(params, buf0, key, stop0)


def generate_beam(forwards, prompt, steps, beam):
    """Beam-search decode: keep the ``beam`` highest-cumulative-log-
    probability continuations at every step (deterministic; the
    sampling knobs live in :func:`generate`).  Rides the kv-cache
    machinery — caches carry ``batch·beam`` rows and are re-gathered
    to each step's surviving parents.

    Returns ``(tokens, scores)``: tokens [batch, beam, prompt_len +
    steps] best-first, scores [batch, beam] — the cumulative log-prob
    of each generated region under the model, exactly re-scorable by
    a teacher-forced forward (tested).  ``beam=1`` equals greedy
    :func:`generate`."""
    from veles_tpu import dtypes
    if not kv_cache_eligible(forwards):
        raise ValueError(
            "beam search decodes on the kv-cache path — this chain "
            "is not cacheable (see kv_cache_eligible)")
    beam = int(beam)
    if beam < 1:
        raise ValueError("beam must be >= 1")
    params = _device_params(forwards)
    prompt = jnp.asarray(prompt, jnp.int32)
    b, p_len = prompt.shape
    total = p_len + int(steps)
    _check_positions(forwards, total)
    vocab = getattr(forwards[-1], "vocab", None)
    if vocab is not None and beam > int(vocab):
        raise ValueError("beam %d > vocab %d" % (beam, vocab))

    buf0 = jnp.zeros((b, total), jnp.int32)
    buf0 = jax.lax.dynamic_update_slice(buf0, prompt, (0, 0))
    caches0 = {i: u.init_cache(b, total, dtypes.compute_dtype())
               for i, u in enumerate(forwards)
               if hasattr(u, "init_cache")}

    pre_step = _make_pre_step(forwards, b)

    def beam_step(params, carry, _):
        bufs, scores, pos, caches = carry        # bufs [b, beam, total]
        tok = jax.lax.dynamic_slice(
            bufs, (0, 0, pos), (b, beam, 1)).reshape(b * beam, 1)
        logits, caches = _chain_step(forwards, params, tok, pos, caches)
        logp = jax.nn.log_softmax(
            logits[:, 0].astype(jnp.float32)).reshape(b, beam, -1)
        # the first expansion starts from `beam` IDENTICAL rows — mask
        # all but row 0 or the top-k would pick the same token k times
        first = pos == jnp.int32(p_len - 1)
        dup_pen = jnp.where(
            first & (jnp.arange(beam)[None, :, None] > 0),
            -jnp.inf, 0.0)
        cand = scores[:, :, None] + logp + dup_pen
        nv = cand.shape[-1]
        scores, flat = jax.lax.top_k(cand.reshape(b, beam * nv), beam)
        parent = flat // nv                       # [b, beam]
        token = (flat % nv).astype(jnp.int32)
        bufs = jnp.take_along_axis(bufs, parent[:, :, None], axis=1)
        bufs = jax.lax.dynamic_update_slice(
            bufs, token[:, :, None], (0, 0, pos + 1))

        def regather(leaf):                       # [b·beam, ...]
            shaped = leaf.reshape((b, beam) + leaf.shape[1:])
            idx = parent.reshape(
                (b, beam) + (1,) * (len(leaf.shape) - 1))
            return jnp.take_along_axis(shaped, idx,
                                       axis=1).reshape(leaf.shape)

        caches = jax.tree_util.tree_map(regather, caches)
        return (bufs, scores, pos + 1, caches), None

    cache_key = (_arch_sig(forwards), b, int(steps), p_len, beam,
                 "beam", str(dtypes.compute_dtype()),
                 str(dtypes.matmul_precision()))
    decode = _decode_cached_beam(
        cache_key, _StepClosure((_make_prefill(forwards), pre_step,
                                 beam_step, beam)))
    return decode(params, buf0, caches0)


class _StepClosure:
    """Always-equal wrapper: the cache keys on ``cache_key`` (the
    architecture signature + batch/lengths/sampler settings) —
    everything the step closure actually varies over — while the
    closure itself rides along uncompared."""

    def __init__(self, fn):
        self.fn = fn

    def __hash__(self):
        return 0

    def __eq__(self, other):
        return isinstance(other, _StepClosure)


def clear_decode_caches():
    """Drop EVERY compiled-decode cache (all five LRUs below), freeing
    the parameter Arrays their step closures pin.  A serving process
    that cycles many large models through decode should call this when
    it retires one — entries otherwise hold the retired chain's units
    (host + device memory) alive until LRU eviction at 16 entries."""
    for cache in (_decode_cached, _decode_cached_kv,
                  _decode_cached_varlen, _decode_cached_kv_varlen,
                  _decode_cached_beam):
        cache.cache_clear()


# NOTE on lifetime: a cached entry's step closure holds the chain's
# units (and therefore their parameter Arrays, host + device) alive
# until LRU eviction — retire models with clear_decode_caches().
@functools.lru_cache(maxsize=16)
def _decode_cached(cache_key, step_closure):
    steps, p_len = cache_key[2], cache_key[3]

    @jax.jit
    def decode(params, buf, key, stop):
        (buf, _, _, _), _ = jax.lax.scan(
            functools.partial(step_closure.fn, params),
            (buf, jnp.int32(p_len), key, stop), None, length=steps)
        return buf

    return track_jit("generate.decode", decode)


@functools.lru_cache(maxsize=16)
def _decode_cached_kv(cache_key, step_closure):
    steps, p_len = cache_key[2], cache_key[3]
    prefill, pre_step, dec_step = step_closure.fn

    @jax.jit
    def decode(params, buf, key, caches, stop):
        if p_len > 1:  # prefill caches over the prompt's predecessors
            if prefill is not None:
                # ONE batched pass over the prompt (TTFT O(1) steps)
                caches = prefill(params, buf[:, :p_len - 1], caches)
            else:
                (buf, _, caches), _ = jax.lax.scan(
                    functools.partial(pre_step, params),
                    (buf, jnp.int32(0), caches), None,
                    length=p_len - 1)
        (buf, _, _, caches, _), _ = jax.lax.scan(
            functools.partial(dec_step, params),
            (buf, jnp.int32(p_len - 1), key, caches, stop), None,
            length=steps)
        return buf

    return track_jit("generate.decode_kv", decode)


@functools.lru_cache(maxsize=16)
def _decode_cached_varlen(cache_key, step_closure):
    total = cache_key[2] + cache_key[3]  # steps + p_len
    vmin = cache_key[-1]                 # min prompt length

    @jax.jit
    def decode(params, buf, key, lens, stop):
        (buf, _, _, _, _), _ = jax.lax.scan(
            functools.partial(step_closure.fn, params),
            (buf, jnp.int32(vmin - 1), key, lens, stop), None,
            length=total - vmin)
        return buf

    return track_jit("generate.decode_varlen", decode)


@functools.lru_cache(maxsize=16)
def _decode_cached_beam(cache_key, step_closure):
    steps, p_len = cache_key[2], cache_key[3]
    prefill, pre_step, beam_step, beam = step_closure.fn

    @jax.jit
    def decode(params, buf, caches):
        if p_len > 1:  # prefill at batch b, then tile beam-ways
            if prefill is not None:
                caches = prefill(params, buf[:, :p_len - 1], caches)
            else:
                (buf, _, caches), _ = jax.lax.scan(
                    functools.partial(pre_step, params),
                    (buf, jnp.int32(0), caches), None,
                    length=p_len - 1)
        b, total = buf.shape
        bufs = jnp.repeat(buf[:, None, :], beam, axis=1)
        caches = jax.tree_util.tree_map(
            lambda x: jnp.repeat(x, beam, axis=0), caches)
        scores = jnp.zeros((b, beam), jnp.float32)
        (bufs, scores, _, _), _ = jax.lax.scan(
            functools.partial(beam_step, params),
            (bufs, scores, jnp.int32(p_len - 1), caches), None,
            length=steps)
        return bufs, scores

    return track_jit("generate.decode_beam", decode)


@functools.lru_cache(maxsize=16)
def _decode_cached_kv_varlen(cache_key, step_closure):
    total = cache_key[2] + cache_key[3]  # steps + p_len

    @jax.jit
    def decode(params, buf, key, caches, lens, stop):
        (buf, _, _, _, _, _), _ = jax.lax.scan(
            functools.partial(step_closure.fn, params),
            (buf, jnp.int32(0), key, caches, lens, stop), None,
            length=total - 1)
        return buf

    return track_jit("generate.decode_kv_varlen", decode)
