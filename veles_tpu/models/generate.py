"""Autoregressive generation from a trained next-token LM.

No reference analogue (the reference had no sequence models at all —
SURVEY.md §5); this completes the LM loop the r5 stack opened:
train (``samples/lm.py``) → snapshot → :func:`generate`.

The whole decode is ONE jitted program: a ``lax.scan`` over decode
steps on a fixed-length token buffer.  Causal attention makes the
fixed buffer exact — positions past the cursor are *future* positions
to every already-generated token, so they cannot influence the logits
the sampler reads (the buffer's tail holds zeros, not padding that
would need masking).  Each step runs the full forward over the buffer
(O(L²) per step without a KV cache — exactness first; a cached decode
is a layout change inside TransformerBlock, not an API change).
"""

import jax
import jax.numpy as jnp


def _chain_logits(forwards, params, tokens):
    h = tokens
    for i, u in enumerate(forwards):
        h = u.apply(params[i], h)
    return h


def generate(forwards, prompt, steps, temperature=0.0, top_k=0,
             key=None):
    """Decode ``steps`` tokens after ``prompt`` [batch, prompt_len]
    (int32) through a forward chain ending in per-token logits
    (Embedding → TransformerBlock × N → TokenProjection).

    - ``temperature`` 0 → greedy argmax; otherwise logits/temperature
      categorical sampling (``key`` required);
    - ``top_k`` > 0 restricts sampling to the k most likely tokens.

    Returns [batch, prompt_len + steps] tokens."""
    params = {i: {name: jnp.asarray(arr.map_read().mem)
                  for name, arr in u.param_arrays().items()}
              for i, u in enumerate(forwards)}
    prompt = jnp.asarray(prompt, jnp.int32)
    b, p_len = prompt.shape
    total = p_len + int(steps)
    if temperature and key is None:
        raise ValueError("sampling (temperature > 0) needs a PRNG key")
    if key is None:
        key = jax.random.key(0)

    buf0 = jnp.zeros((b, total), jnp.int32)
    buf0 = jax.lax.dynamic_update_slice(buf0, prompt, (0, 0))

    def sample(logits, k):
        if temperature:
            z = logits / float(temperature)
            if top_k:
                kth = jnp.sort(z, axis=-1)[:, -int(top_k)][:, None]
                z = jnp.where(z < kth, -jnp.inf, z)
            return jax.random.categorical(k, z).astype(jnp.int32)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def step(carry, _):
        buf, pos, k = carry
        logits = _chain_logits(forwards, params, buf)
        # logits at the cursor's predecessor predict the cursor token
        row = jax.lax.dynamic_slice(
            logits, (0, pos - 1, 0), (b, 1, logits.shape[-1]))[:, 0]
        k, sub = jax.random.split(k)
        nxt = sample(row, sub)
        buf = jax.lax.dynamic_update_slice(buf, nxt[:, None], (0, pos))
        return (buf, pos + 1, k), None

    @jax.jit
    def decode(buf, key):
        (buf, _, _), _ = jax.lax.scan(
            step, (buf, jnp.int32(p_len), key), None, length=int(steps))
        return buf

    return decode(buf0, key)
