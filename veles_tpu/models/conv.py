"""Convolutional layers (reconstruction of znicz conv, surface per
manualrst_veles_algorithms.rst: grouping, padding, stride — "sliding" in
Veles terms — and Deconvolution).

Data layout is NHWC with HWIO kernels — the layouts XLA:TPU tiles onto
the MXU without transposes.  The convolution itself is
``lax.conv_general_dilated`` (one XLA op; the reference lowered conv to
im2col + its hand-tiled GEMM).
"""

import jax
import jax.numpy as jnp
import numpy

from veles_tpu import dtypes
from veles_tpu.models.activations import get_activation
from veles_tpu.models.nn_units import ForwardBase


def _pair(v):
    if isinstance(v, (tuple, list)):
        return tuple(int(x) for x in v[:2])
    return (int(v), int(v))


class Conv(ForwardBase):
    """y = activation(conv(x, W) + b), x: [N, H, W, C]
    (znicz conv.Conv; kwargs kx/ky/n_kernels/sliding/padding match the
    reference surface, grouping via ``n_groups``)."""

    ACTIVATION = "linear"

    def __init__(self, workflow, n_kernels=None, kx=3, ky=3,
                 sliding=(1, 1), padding="same", n_groups=1,
                 activation=None, **kwargs):
        super(Conv, self).__init__(workflow, **kwargs)
        if n_kernels is None:
            raise ValueError("n_kernels is required")
        self.n_kernels = int(n_kernels)
        self.kx, self.ky = int(kx), int(ky)
        #: user-facing (sliding_x, sliding_y) — the znicz convention
        #: (kx = horizontal); internally NHWC wants (stride_H, stride_W)
        self.sliding = _pair(sliding)
        self.padding = padding  # "same" | "valid" | ((t,b),(l,r)) | int
        self.n_groups = int(n_groups)
        self.activation = activation or self.ACTIVATION

    @property
    def _hw_strides(self):
        sx, sy = self.sliding
        return (sy, sx)

    def _lax_padding(self):
        if isinstance(self.padding, str):
            return self.padding.upper()
        if isinstance(self.padding, int):
            p = self.padding
            return ((p, p), (p, p))
        return tuple(tuple(int(x) for x in p) for p in self.padding)

    def output_shape_for(self, input_shape):
        n, h, w, _ = input_shape
        out = jax.eval_shape(
            lambda x, k: self._conv(x, k),
            jax.ShapeDtypeStruct(input_shape, jnp.float32),
            jax.ShapeDtypeStruct(self._kernel_shape(input_shape[-1]),
                                 jnp.float32))
        return out.shape

    def _kernel_shape(self, in_channels):
        return (self.ky, self.kx, in_channels // self.n_groups,
                self.n_kernels)

    def _conv(self, x, kernel):
        # BOTH operands cast to the compute dtype and the output kept in
        # it: the conv trunk's activations are the HBM-bandwidth hot
        # spot (bf16 halves the traffic), and the conv VJP needs
        # matching operand/cotangent dtypes — a bf16-in/f32-out mix is
        # rejected by lax.conv.  The MXU accumulates in f32 internally
        # regardless; the loss is computed in f32 at the evaluator.
        # (A space-to-depth rewrite of the AlexNet 11x11/4 stem was
        # measured on v5e — per-minibatch blocking AND a pre-blocked
        # dataset both ran slower than XLA's native strided conv, so
        # no stem special-case exists here.)
        cd = dtypes.compute_dtype()
        return jax.lax.conv_general_dilated(
            x.astype(cd), kernel.astype(cd),
            window_strides=self._hw_strides,
            padding=self._lax_padding(),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=self.n_groups,
            precision=dtypes.matmul_precision())

    def fill_params(self):
        in_ch = self.input.shape[-1]
        kshape = self._kernel_shape(in_ch)
        fan_in = self.kx * self.ky * in_ch // self.n_groups
        fan_out = self.n_kernels
        self.weights.reset(numpy.zeros(kshape, numpy.float32))
        self._fill(self.weights.mem, self.weights_filling,
                   self.weights_stddev, fan_in, fan_out)
        if self.include_bias:
            self.bias.reset(numpy.zeros((self.n_kernels,), numpy.float32))
            self._fill(self.bias.mem, self.bias_filling,
                       self.bias_stddev or 0.0, fan_in, fan_out)

    def apply(self, params, x):
        y = self._conv(x, params["weights"])
        if self.include_bias:
            y = y + params["bias"].astype(y.dtype)
        return get_activation(self.activation)(y)

    def export_config(self):
        return {"n_kernels": self.n_kernels, "kx": self.kx, "ky": self.ky,
                "sliding": list(self.sliding), "padding": self.padding,
                "n_groups": self.n_groups, "activation": self._export_activation(),
                "include_bias": self.include_bias}


class ConvTanh(Conv):
    ACTIVATION = "tanh"


class ConvRELU(Conv):
    ACTIVATION = "relu"


class ConvStrictRELU(Conv):
    ACTIVATION = "strict_relu"


class Deconv(ForwardBase):
    """Transposed convolution (znicz deconv; extras item 1) — used by the
    convolutional autoencoders."""

    ACTIVATION = "linear"

    def __init__(self, workflow, n_kernels=None, kx=3, ky=3,
                 sliding=(1, 1), padding="same", activation=None, **kwargs):
        super(Deconv, self).__init__(workflow, **kwargs)
        if n_kernels is None:
            raise ValueError("n_kernels is required")
        self.n_kernels = int(n_kernels)
        self.kx, self.ky = int(kx), int(ky)
        self.sliding = _pair(sliding)  # (sx, sy), znicz convention
        self.padding = padding
        self.activation = activation or self.ACTIVATION

    def _kernel_shape(self, in_channels):
        return (self.ky, self.kx, self.n_kernels, in_channels)

    def _deconv(self, x, kernel):
        cd = dtypes.compute_dtype()  # see Conv._conv dtype note
        pad = self.padding.upper() if isinstance(self.padding, str) \
            else self.padding
        sx, sy = self.sliding
        return jax.lax.conv_transpose(
            x.astype(cd), kernel.astype(cd),
            strides=(sy, sx), padding=pad,
            dimension_numbers=("NHWC", "HWOI", "NHWC"),
            precision=dtypes.matmul_precision())

    def output_shape_for(self, input_shape):
        out = jax.eval_shape(
            lambda x, k: self._deconv(x, k),
            jax.ShapeDtypeStruct(input_shape, jnp.float32),
            jax.ShapeDtypeStruct(self._kernel_shape(input_shape[-1]),
                                 jnp.float32))
        return out.shape

    def fill_params(self):
        in_ch = self.input.shape[-1]
        kshape = self._kernel_shape(in_ch)
        fan_in = self.kx * self.ky * in_ch
        fan_out = self.n_kernels
        self.weights.reset(numpy.zeros(kshape, numpy.float32))
        self._fill(self.weights.mem, self.weights_filling,
                   self.weights_stddev, fan_in, fan_out)
        if self.include_bias:
            self.bias.reset(numpy.zeros((self.n_kernels,), numpy.float32))
            self._fill(self.bias.mem, self.bias_filling,
                       self.bias_stddev or 0.0, fan_in, fan_out)

    def apply(self, params, x):
        y = self._deconv(x, params["weights"])
        if self.include_bias:
            y = y + params["bias"]
        return get_activation(self.activation)(y.astype(jnp.float32))

    def export_config(self):
        return {"n_kernels": self.n_kernels, "kx": self.kx, "ky": self.ky,
                "sliding": list(self.sliding), "padding": self.padding,
                "activation": self._export_activation(),
                "include_bias": self.include_bias}
