"""Convolutional layers (reconstruction of znicz conv, surface per
manualrst_veles_algorithms.rst: grouping, padding, stride — "sliding" in
Veles terms — and Deconvolution).

Data layout is NHWC with HWIO kernels — the layouts XLA:TPU tiles onto
the MXU without transposes.  The convolution itself is
``lax.conv_general_dilated`` (one XLA op; the reference lowered conv to
im2col + its hand-tiled GEMM).
"""

import jax
import jax.numpy as jnp
import numpy

from veles_tpu import dtypes
from veles_tpu.models.activations import get_activation
from veles_tpu.models.nn_units import ForwardBase


def _pair(v):
    if isinstance(v, (tuple, list)):
        return tuple(int(x) for x in v[:2])
    return (int(v), int(v))


def validate_space_to_depth(h, w, ky, kx, n):
    """Raise unless a stride-n VALID ky×kx conv over [h, w] produces
    the same output from the blocked form — i.e. (h-ky) and (w-kx)
    are stride multiples AND the blocked VALID output count matches
    the logical one.  Loaders/samples that pre-block data call this
    with the model's stem geometry (misalignment would silently add
    border outputs computed from block padding)."""
    for dim, k in ((h, ky), (w, kx)):
        if (dim - k) % n:
            raise ValueError(
                "space_to_depth=%d misaligned: (%d - %d) %% %d != 0"
                % (n, dim, k, n))
        logical = (dim - k) // n + 1
        blocked = -(-dim // n) - (-(-k // n)) + 1
        if logical != blocked:
            raise ValueError(
                "space_to_depth=%d: blocked VALID output %d != "
                "logical %d over extent %d (kernel %d)"
                % (n, blocked, logical, dim, k))


def space_to_depth(x, n):
    """[B, H, W, C] → [B, ceil(H/n), ceil(W/n), n²·C] (zero-padded to
    block multiples; block channel layout (dh, dw, c)).  Loaders call
    this to pre-block data for a ``Conv(space_to_depth=n)`` stem —
    and should call :func:`validate_space_to_depth` with the stem
    geometry first."""
    b, h, w, c = x.shape
    hp = -h % n
    wp = -w % n
    if hp or wp:
        x = jnp.pad(x, ((0, 0), (0, hp), (0, wp), (0, 0)))
    hb, wb = (h + hp) // n, (w + wp) // n
    x = x.reshape(b, hb, n, wb, n, c)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(b, hb, wb, n * n * c)


class Conv(ForwardBase):
    """y = activation(conv(x, W) + b), x: [N, H, W, C]
    (znicz conv.Conv; kwargs kx/ky/n_kernels/sliding/padding match the
    reference surface, grouping via ``n_groups``)."""

    ACTIVATION = "linear"

    def __init__(self, workflow, n_kernels=None, kx=3, ky=3,
                 sliding=(1, 1), padding="same", n_groups=1,
                 activation=None, space_to_depth=0,
                 space_to_depth_hw=None, **kwargs):
        super(Conv, self).__init__(workflow, **kwargs)
        if n_kernels is None:
            raise ValueError("n_kernels is required")
        self.n_kernels = int(n_kernels)
        self.kx, self.ky = int(kx), int(ky)
        #: user-facing (sliding_x, sliding_y) — the znicz convention
        #: (kx = horizontal); internally NHWC wants (stride_H, stride_W)
        self.sliding = _pair(sliding)
        self.padding = padding  # "same" | "valid" | ((t,b),(l,r)) | int
        self.n_groups = int(n_groups)
        self.activation = activation or self.ACTIVATION
        #: stride-matched space-to-depth stem (TPU emitter fix for
        #: tiny-C strided stems like AlexNet's 11×11/4 over RGB: the
        #: blocked form measured 5.42 vs 7.88 ms fwd+dk on v5e,
        #: ROUND5_NOTES.md §1a).  Weights stay in the LOGICAL
        #: [ky, kx, C, O] convention — the blocked kernel is built
        #: in-graph, so export/snapshot/autodiff are unchanged.  The
        #: loader must feed pre-blocked data (``space_to_depth()``).
        #: NOT supported by the C++ runner's Conv (runtime/units.cc
        #: computes the plain strided form) — export with
        #: space_to_depth=0 for package_export targets.
        self.space_to_depth = int(space_to_depth or 0)
        #: (hb, wb) of the blocked input when the loader stores it
        #: FLAT [batch, hb·wb·n²·C] — 4D-blocked dataset layouts
        #: gather pathologically (ROUND5_NOTES.md §1c), so the fast
        #: path is flat storage + this in-graph reshape
        self.space_to_depth_hw = tuple(space_to_depth_hw) \
            if space_to_depth_hw else None
        if self.space_to_depth:
            if self.n_groups != 1:
                raise ValueError("space_to_depth requires n_groups=1")
            if self.sliding != (self.space_to_depth,) * 2:
                raise ValueError(
                    "space_to_depth=%d requires sliding=(%d, %d)"
                    % ((self.space_to_depth,) * 3))
            if not (isinstance(self.padding, str)
                    and self.padding.lower() == "valid"):
                raise ValueError("space_to_depth requires VALID padding")

    @property
    def _hw_strides(self):
        sx, sy = self.sliding
        return (sy, sx)

    def _lax_padding(self):
        if isinstance(self.padding, str):
            return self.padding.upper()
        if isinstance(self.padding, int):
            p = self.padding
            return ((p, p), (p, p))
        return tuple(tuple(int(x) for x in p) for p in self.padding)

    def _blocked_in_channels(self, input_shape):
        """Per-block input channels (n²·C_logical) from either the 4D
        blocked layout or the flat [batch, hb·wb·n²·C] one."""
        if len(input_shape) == 2 and self.space_to_depth:
            if not self.space_to_depth_hw:
                raise ValueError(
                    "flat space_to_depth input needs space_to_depth_hw")
            hb, wb = self.space_to_depth_hw
            return input_shape[-1] // (hb * wb)
        return input_shape[-1]

    def output_shape_for(self, input_shape):
        kshape = self._kernel_shape(
            self._blocked_in_channels(input_shape))
        out = jax.eval_shape(
            lambda x, k: self._conv(x, k),
            jax.ShapeDtypeStruct(input_shape, jnp.float32),
            jax.ShapeDtypeStruct(kshape, jnp.float32))
        return out.shape

    def _kernel_shape(self, in_channels):
        if self.space_to_depth:
            in_channels //= self.space_to_depth ** 2
        return (self.ky, self.kx, in_channels // self.n_groups,
                self.n_kernels)

    def _blocked_kernel(self, kernel):
        """Logical [ky, kx, C, O] → blocked [kby, kbx, n²·C, O]
        matching ``space_to_depth``'s (dh, dw, c) channel layout.
        Built in-graph: tiny (≤ tens of KB), and autodiff maps the
        blocked-kernel cotangent back onto the logical weights."""
        n = self.space_to_depth
        ky, kx, c, o = kernel.shape
        kby, kbx = -(-ky // n), -(-kx // n)
        kp = jnp.pad(kernel, ((0, kby * n - ky), (0, kbx * n - kx),
                              (0, 0), (0, 0)))
        kp = kp.reshape(kby, n, kbx, n, c, o)
        return kp.transpose(0, 2, 1, 3, 4, 5).reshape(
            kby, kbx, n * n * c, o)

    def _unflatten_s2d(self, x):
        if x.ndim == 2 and self.space_to_depth:
            c = self._blocked_in_channels(x.shape)
            hb, wb = self.space_to_depth_hw
            x = x.reshape(x.shape[0], hb, wb, c)
        return x

    def _conv(self, x, kernel):
        if self.space_to_depth:
            x = self._unflatten_s2d(x)
            # blocked stem: stride-n VALID conv over [B, H, W, C]
            # becomes a stride-1 VALID conv over the pre-blocked
            # [B, ceil(H/n), ceil(W/n), n²·C] input.  The caller must
            # pre-block with ``space_to_depth()`` and guarantee
            # (H - ky) % n == 0 so the blocked output equals the
            # logical one (AlexNet's 227/11/4 stem does).
            cd = dtypes.compute_dtype()
            return jax.lax.conv_general_dilated(
                x.astype(cd), self._blocked_kernel(kernel).astype(cd),
                window_strides=(1, 1), padding="VALID",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                precision=dtypes.matmul_precision())
        # BOTH operands cast to the compute dtype and the output kept in
        # it: the conv trunk's activations are the HBM-bandwidth hot
        # spot (bf16 halves the traffic), and the conv VJP needs
        # matching operand/cotangent dtypes — a bf16-in/f32-out mix is
        # rejected by lax.conv.  The MXU accumulates in f32 internally
        # regardless; the loss is computed in f32 at the evaluator.
        # (The space_to_depth branch above is the r5 stem rewrite:
        # 2.2 ms faster in isolation but net-negative in the full
        # step because of the blocked dataset's gather layout — see
        # ROUND5_NOTES.md §1c; it therefore ships opt-in.)
        cd = dtypes.compute_dtype()
        return jax.lax.conv_general_dilated(
            x.astype(cd), kernel.astype(cd),
            window_strides=self._hw_strides,
            padding=self._lax_padding(),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=self.n_groups,
            precision=dtypes.matmul_precision())

    def fill_params(self):
        in_ch = self._blocked_in_channels(self.input.shape)
        kshape = self._kernel_shape(in_ch)
        fan_in = self.kx * self.ky * kshape[2]
        fan_out = self.n_kernels
        self.weights.reset(numpy.zeros(kshape, numpy.float32))
        self._fill(self.weights.mem, self.weights_filling,
                   self.weights_stddev, fan_in, fan_out)
        if self.include_bias:
            self.bias.reset(numpy.zeros((self.n_kernels,), numpy.float32))
            self._fill(self.bias.mem, self.bias_filling,
                       self.bias_stddev or 0.0, fan_in, fan_out)

    def apply(self, params, x):
        y = self._conv(x, params["weights"])
        if self.include_bias:
            y = y + params["bias"].astype(y.dtype)
        return get_activation(self.activation)(y)

    def export_config(self):
        cfg = {"n_kernels": self.n_kernels, "kx": self.kx, "ky": self.ky,
               "sliding": list(self.sliding), "padding": self.padding,
               "n_groups": self.n_groups, "activation": self._export_activation(),
               "include_bias": self.include_bias}
        if self.space_to_depth:
            cfg["space_to_depth"] = self.space_to_depth
            if self.space_to_depth_hw:
                cfg["space_to_depth_hw"] = list(self.space_to_depth_hw)
        return cfg


class ConvTanh(Conv):
    ACTIVATION = "tanh"


class ConvRELU(Conv):
    ACTIVATION = "relu"


class ConvStrictRELU(Conv):
    ACTIVATION = "strict_relu"


class Deconv(ForwardBase):
    """Transposed convolution (znicz deconv; extras item 1) — used by the
    convolutional autoencoders."""

    ACTIVATION = "linear"

    def __init__(self, workflow, n_kernels=None, kx=3, ky=3,
                 sliding=(1, 1), padding="same", activation=None, **kwargs):
        super(Deconv, self).__init__(workflow, **kwargs)
        if n_kernels is None:
            raise ValueError("n_kernels is required")
        self.n_kernels = int(n_kernels)
        self.kx, self.ky = int(kx), int(ky)
        self.sliding = _pair(sliding)  # (sx, sy), znicz convention
        self.padding = padding
        self.activation = activation or self.ACTIVATION

    def _kernel_shape(self, in_channels):
        return (self.ky, self.kx, self.n_kernels, in_channels)

    def _deconv(self, x, kernel):
        cd = dtypes.compute_dtype()  # see Conv._conv dtype note
        pad = self.padding.upper() if isinstance(self.padding, str) \
            else self.padding
        sx, sy = self.sliding
        return jax.lax.conv_transpose(
            x.astype(cd), kernel.astype(cd),
            strides=(sy, sx), padding=pad,
            dimension_numbers=("NHWC", "HWOI", "NHWC"),
            precision=dtypes.matmul_precision())

    def output_shape_for(self, input_shape):
        out = jax.eval_shape(
            lambda x, k: self._deconv(x, k),
            jax.ShapeDtypeStruct(input_shape, jnp.float32),
            jax.ShapeDtypeStruct(self._kernel_shape(input_shape[-1]),
                                 jnp.float32))
        return out.shape

    def fill_params(self):
        in_ch = self.input.shape[-1]
        kshape = self._kernel_shape(in_ch)
        fan_in = self.kx * self.ky * in_ch
        fan_out = self.n_kernels
        self.weights.reset(numpy.zeros(kshape, numpy.float32))
        self._fill(self.weights.mem, self.weights_filling,
                   self.weights_stddev, fan_in, fan_out)
        if self.include_bias:
            self.bias.reset(numpy.zeros((self.n_kernels,), numpy.float32))
            self._fill(self.bias.mem, self.bias_filling,
                       self.bias_stddev or 0.0, fan_in, fan_out)

    def apply(self, params, x):
        y = self._deconv(x, params["weights"])
        if self.include_bias:
            y = y + params["bias"]
        return get_activation(self.activation)(y.astype(jnp.float32))

    def export_config(self):
        return {"n_kernels": self.n_kernels, "kx": self.kx, "ky": self.ky,
                "sliding": list(self.sliding), "padding": self.padding,
                "activation": self._export_activation(),
                "include_bias": self.include_bias}
