"""Kohonen self-organizing maps (reconstruction of the znicz Kohonen
unit family — manualrst_veles_algorithms.rst "Kohonen maps", the
SpamKohonen/DemoKohonen workflows).

TPU-native formulation: one jitted step per minibatch computes all
sample↔neuron distances as a GEMM-shaped expression on the MXU, takes
winners, and applies the Gaussian-neighborhood batch update — the
reference spread this over several OpenCL kernels (distance, argmin,
gravity, weight update).
"""

import jax
import jax.numpy as jnp
import numpy

from veles_tpu.accelerated_units import AcceleratedUnit
from veles_tpu.memory import Array
from veles_tpu.result_provider import IResultProvider
from veles_tpu.units import MissingDemand
from veles_tpu import prng as prng_mod


def _grid(sy, sx):
    yy, xx = numpy.mgrid[0:sy, 0:sx]
    return numpy.stack([yy.ravel(), xx.ravel()], axis=1).astype(
        numpy.float32)


class KohonenForward(AcceleratedUnit):
    """Best-matching-unit lookup: ``output[b]`` = index of the nearest
    neuron on the (sy, sx) grid (znicz KohonenForward role)."""

    READS = ("input", "weights")
    WRITES = ("output",)

    def __init__(self, workflow, weights=None, shape=(8, 8), **kwargs):
        super(KohonenForward, self).__init__(workflow, **kwargs)
        self.input = None
        self.weights = weights if weights is not None else Array()
        self.shape = tuple(shape)
        self.output = Array()
        self.demand("input")

    def initialize(self, device=None, **kwargs):
        if not isinstance(self.input, Array) or not bool(self.input):
            raise MissingDemand(self, {"input"})
        self.output.reset(numpy.zeros((self.input.shape[0],),
                                      numpy.int32))
        super(KohonenForward, self).initialize(device=device, **kwargs)

    @staticmethod
    def bmu(weights, x):
        """[batch] winner indices; distance via the expanded-norm GEMM
        (‖x−w‖² = ‖x‖² − 2x·wᵀ + ‖w‖², the MXU carries the cross
        term)."""
        x2 = jnp.sum(x * x, axis=1, keepdims=True)
        w2 = jnp.sum(weights * weights, axis=1)[None, :]
        cross = x @ weights.T
        d = x2 - 2.0 * cross + w2
        return jnp.argmin(d, axis=1).astype(jnp.int32), d

    def step(self, input, weights):
        x = input.reshape(input.shape[0], -1)
        winners, _ = self.bmu(weights, x)
        return {"output": winners}


class KohonenTrainer(AcceleratedUnit):
    """Batch SOM update (znicz KohonenTrainer role): winners +
    Gaussian neighborhood on the grid, learning rate and radius
    annealed over ``time`` steps."""

    FUSABLE = False  # owns its dispatch (donated weights)

    def __init__(self, workflow, loader=None, shape=(8, 8),
                 sigma0=None, sigma_decay=200.0, learning_rate=0.5,
                 lr_decay=200.0, prng_key="kohonen", **kwargs):
        super(KohonenTrainer, self).__init__(workflow, **kwargs)
        self.loader = loader
        self.shape = tuple(shape)
        self.sigma0 = sigma0 if sigma0 is not None \
            else max(self.shape) / 2.0
        self.sigma_decay = sigma_decay
        self.learning_rate = learning_rate
        self.lr_decay = lr_decay
        self.prng = prng_mod.get(prng_key)
        self.weights = Array()
        self.time = 0
        self.qerror = Array()   # mean quantization error (host metric)
        self.demand("loader")

    def init_unpickled(self):
        super(KohonenTrainer, self).init_unpickled()
        self._step_ = None

    @property
    def n_neurons(self):
        return self.shape[0] * self.shape[1]

    def initialize(self, device=None, **kwargs):
        if self.loader is None:
            raise MissingDemand(self, {"loader"})
        features = int(numpy.prod(self.loader.minibatch_data.shape[1:]))
        if not bool(self.weights):
            w = numpy.zeros((self.n_neurons, features), numpy.float32)
            self.prng.fill(w, -0.1, 0.1)
            self.weights.reset(w)
        self.qerror.reset(numpy.zeros((), numpy.float32))
        super(KohonenTrainer, self).initialize(device=device, **kwargs)

    def _build_step(self):
        coords = jnp.asarray(_grid(*self.shape))

        def step(weights, x, size, t):
            x = x.reshape(x.shape[0], -1)
            winners, d = KohonenForward.bmu(weights, x)
            mask = (jnp.arange(x.shape[0]) < size).astype(jnp.float32)
            qerr = jnp.sum(
                jnp.sqrt(jnp.maximum(
                    jnp.take_along_axis(d, winners[:, None], 1)[:, 0],
                    0.0)) * mask) / jnp.maximum(size, 1)
            sigma = self.sigma0 * jnp.exp(-t / self.sigma_decay)
            lr = self.learning_rate * jnp.exp(-t / self.lr_decay)
            # neighborhood of each sample's winner over all neurons
            wc = coords[winners]                      # [b, 2]
            d2 = jnp.sum(
                (wc[:, None, :] - coords[None, :, :]) ** 2, axis=-1)
            h = jnp.exp(-d2 / (2.0 * sigma * sigma)) * mask[:, None]
            # batch update: w_n += lr * Σ_b h_bn (x_b − w_n) / Σ_b h_bn
            num = h.T @ x                             # [n, f]
            den = jnp.sum(h, axis=0)[:, None]
            target = num / jnp.maximum(den, 1e-12)
            gate = (den > 1e-12).astype(jnp.float32)
            new_w = weights + lr * gate * (target - weights)
            return new_w, qerr

        from veles_tpu.telemetry import track_jit
        return track_jit("kohonen.step",
                         jax.jit(step, donate_argnums=(0,)))

    def run(self):
        if self._step_ is None:
            self._step_ = self._build_step()
        l = self.loader
        new_w, qerr = self._step_(
            self.weights.donatable_devmem(), l.minibatch_data.devmem,
            jnp.int32(l.minibatch_size), jnp.float32(self.time))
        self.weights.devmem = new_w
        self.qerror.devmem = qerr
        self.time += 1

    def step(self, **tensors):
        raise RuntimeError("KohonenTrainer dispatches its own program")


class KohonenDecision(AcceleratedUnit, IResultProvider):
    """Epoch loop control for SOM training (no gradient/error signal —
    stops on max_epochs; znicz used its KohonenDecision similarly)."""

    FUSABLE = False

    def __init__(self, workflow, max_epochs=10, **kwargs):
        from veles_tpu.mutable import Bool
        super(KohonenDecision, self).__init__(workflow, **kwargs)
        self.max_epochs = max_epochs
        self.loader = None
        self.trainer = None
        self.complete = Bool(False, "complete")
        self.epoch_qerror = []
        self.demand("loader", "trainer")

    def run(self):
        l = self.loader
        if l.train_ended:
            self.trainer.qerror.map_read()
            self.epoch_qerror.append(float(self.trainer.qerror.mem))
            self.info("epoch %d: quantization error %.4f",
                      l.epoch_number, self.epoch_qerror[-1])
            if l.epoch_number >= self.max_epochs:
                self.complete.set(True)
                if self._workflow is not None:
                    self._workflow.on_workflow_finished()

    def get_metric_values(self):
        return {"quantization_error":
                self.epoch_qerror[-1] if self.epoch_qerror else None}
