"""models — the NN layer/trainer surface (reconstruction of the Znicz
plugin, whose source is absent upstream — see SURVEY.md §0; the surface
is pinned by docs/source/manualrst_veles_algorithms.rst:150-164 and
BASELINE.json's configs).

TPU-first redesign of the training path: the reference hand-wrote one
backward (GD) unit per layer kind with bespoke CUDA/OpenCL gradient
kernels; here the :class:`~veles_tpu.models.gd.GradientDescent` trainer
unit composes the forward chain + evaluator loss into ONE jitted
``jax.value_and_grad`` program with the solver update fused in — forward,
backward, optimizer, and (when data-parallel) the gradient ``psum`` all
execute as a single XLA program per minibatch.

Modules:
- nn_units:    ForwardBase (params, smart weight init, per-layer hypers)
- activations: activation registry (linear/tanh/relu/sigmoid/sincos/...)
- all2all:     fully-connected layers incl. softmax head
- conv:        convolution (+grouping/padding/sliding) and deconvolution
- pooling:     max/avg pooling and depooling
- dropout:     dropout forward
- evaluator:   softmax / MSE evaluators (loss + error metrics)
- solvers:     sgd / momentum / adagrad / adadelta / adam registry
- lr_adjust:   learning-rate schedules
- gd:          the fused autodiff trainer
- decision:    DecisionGD stopping logic + Rollback
"""

from veles_tpu.models.all2all import (  # noqa: F401
    All2All, All2AllRELU, All2AllSigmoid, All2AllSoftmax,
    All2AllStrictRELU, All2AllTanh)
from veles_tpu.models.activations import Activation  # noqa: F401
from veles_tpu.models.conv import Conv, ConvRELU, ConvTanh, Deconv  # noqa: F401
from veles_tpu.models.pooling import (  # noqa: F401
    AvgPooling, Depooling, MaxPooling)
from veles_tpu.models.dropout import DropoutForward  # noqa: F401
from veles_tpu.models.lrn import LRNormalizerForward  # noqa: F401
from veles_tpu.models.attention import MultiHeadAttention  # noqa: F401
from veles_tpu.models.recurrent import (  # noqa: F401
    LSTM, LastTimestep, SimpleRNN)
from veles_tpu.models.rbm import BernoulliRBM  # noqa: F401
from veles_tpu.models.kohonen import (  # noqa: F401
    KohonenDecision, KohonenForward, KohonenTrainer)
from veles_tpu.models.embedding import Embedding  # noqa: F401
from veles_tpu.models.moe import MoE  # noqa: F401
from veles_tpu.models.transformer import (  # noqa: F401
    MeanPoolSeq, TokenProjection, TransformerBlock)
from veles_tpu.models.evaluator import (  # noqa: F401
    EvaluatorMSE, EvaluatorNextToken, EvaluatorSoftmax)
# NOTE: the decode FUNCTION ``generate`` is deliberately not re-bound
# here — it would shadow the ``veles_tpu.models.generate`` MODULE
# attribute; reach it as ``veles_tpu.models.generate.generate``
from veles_tpu.models.generate import (  # noqa: F401
    clear_decode_caches, generate_beam, kv_cache_eligible)
from veles_tpu.models.gd import GradientDescent  # noqa: F401
from veles_tpu.models.decision import DecisionGD, Rollback  # noqa: F401
