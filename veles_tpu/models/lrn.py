"""Local response normalization (reconstruction of the znicz
``normalization.LRNormalizerForward`` unit; surface per
manualrst_veles_algorithms.rst:150-164 item 6 — AlexNet needs it across
channels).

    y = x / (k + alpha * sum_{j in window(c)} x_j^2) ** beta

The channel-window sum is one ``lax.reduce_window`` over the C axis of
NHWC — XLA fuses the whole expression into the surrounding program, so
there is no standalone kernel to write.
"""

import jax
import jax.numpy as jnp

from veles_tpu.models.nn_units import ForwardBase


class LRNormalizerForward(ForwardBase):
    """Cross-channel LRN (znicz LRNormalizerForward surface: ``alpha``,
    ``beta``, ``n`` window size, ``k`` bias; AlexNet-paper defaults)."""

    PARAMS = ()

    def __init__(self, workflow, alpha=1e-4, beta=0.75, n=5, k=2.0,
                 **kwargs):
        super(LRNormalizerForward, self).__init__(workflow, **kwargs)
        self.alpha = float(alpha)
        self.beta = float(beta)
        self.n = int(n)
        self.k = float(k)

    def fill_params(self):
        pass

    def export_config(self):
        return {"alpha": self.alpha, "beta": self.beta,
                "n": self.n, "k": self.k}

    def output_shape_for(self, input_shape):
        return input_shape

    def apply(self, params, x):
        import numpy
        from veles_tpu import dtypes
        sq = x * x
        half = self.n // 2
        c = x.shape[-1]
        # The channel window sum is a BANDED MATMUL: channels live on the
        # TPU lane dimension, where a reduce_window would lower to n-1
        # cross-lane shifts (measured: ~38% of the whole AlexNet step).
        # ssum = sq @ band rides the MXU instead and its VJP is just the
        # transposed band matmul.
        # band[src, dst] = 1 iff channel src falls in dst's window
        # [dst-half, dst+n-1-half] (same semantics as a reduce_window
        # padded (half, n-1-half))
        src = numpy.arange(c)[:, None]
        dst = numpy.arange(c)[None, :]
        band = ((dst - src) <= half) & ((src - dst) <= (self.n - 1 - half))
        cd = dtypes.compute_dtype()
        ssum = jax.lax.dot_general(
            sq.astype(cd), jnp.asarray(band.astype(numpy.float32), cd),
            (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(x.dtype)
        s = self.k + self.alpha * ssum
        if self.beta == 0.75:
            # s^-0.75 = rsqrt(s)·sqrt(rsqrt(s)): cheap VPU ops (lax.pow
            # lowers to exp/log)
            r = jax.lax.rsqrt(s)
            return x * (r * jnp.sqrt(r))
        return x * jax.lax.pow(s, -self.beta)
