"""Local response normalization (reconstruction of the znicz
``normalization.LRNormalizerForward`` unit; surface per
manualrst_veles_algorithms.rst:150-164 item 6 — AlexNet needs it across
channels).

    y = x / (k + alpha * sum_{j in window(c)} x_j^2) ** beta

The channel-window sum is one ``lax.reduce_window`` over the C axis of
NHWC — XLA fuses the whole expression into the surrounding program, so
there is no standalone kernel to write.
"""

import jax
import jax.numpy as jnp

from veles_tpu.models.nn_units import ForwardBase


class LRNormalizerForward(ForwardBase):
    """Cross-channel LRN (znicz LRNormalizerForward surface: ``alpha``,
    ``beta``, ``n`` window size, ``k`` bias; AlexNet-paper defaults)."""

    PARAMS = ()

    def __init__(self, workflow, alpha=1e-4, beta=0.75, n=5, k=2.0,
                 **kwargs):
        super(LRNormalizerForward, self).__init__(workflow, **kwargs)
        self.alpha = float(alpha)
        self.beta = float(beta)
        self.n = int(n)
        self.k = float(k)

    def fill_params(self):
        pass

    def export_config(self):
        return {"alpha": self.alpha, "beta": self.beta,
                "n": self.n, "k": self.k}

    def output_shape_for(self, input_shape):
        return input_shape

    def apply(self, params, x):
        # On TPU: plain-autodiff band-matmul LRN (veles_tpu/ops/lrn.py
        # documents the measured formulation shootout, including the
        # r5 pallas kernels that win in isolation but lose in-graph to
        # the 4D→2D relayout copy).  Off-TPU the same math as shifted
        # adds — cheap on CPU, no band constant.
        if jax.default_backend() == "tpu":
            from veles_tpu.ops.lrn import lrn
            return lrn(x, self.alpha, self.beta, self.n, self.k)
        sq = x * x
        half = self.n // 2
        c = x.shape[-1]
        pad = jnp.pad(sq, [(0, 0)] * (x.ndim - 1) +
                      [(half, self.n - 1 - half)])
        ssum = pad[..., 0:c]
        for i in range(1, self.n):
            ssum = ssum + pad[..., i:i + c]
        s = self.k + self.alpha * ssum
        if self.beta == 0.75:
            # s^-0.75 = rsqrt(s)·sqrt(rsqrt(s)): cheap VPU ops (lax.pow
            # lowers to exp/log)
            r = jax.lax.rsqrt(s)
            return x * (r * jnp.sqrt(r))
        return x * jax.lax.pow(s, -self.beta)
