"""Local response normalization (reconstruction of the znicz
``normalization.LRNormalizerForward`` unit; surface per
manualrst_veles_algorithms.rst:150-164 item 6 — AlexNet needs it across
channels).

    y = x / (k + alpha * sum_{j in window(c)} x_j^2) ** beta

The channel-window sum is one ``lax.reduce_window`` over the C axis of
NHWC — XLA fuses the whole expression into the surrounding program, so
there is no standalone kernel to write.
"""

import jax
import jax.numpy as jnp

from veles_tpu.models.nn_units import ForwardBase


class LRNormalizerForward(ForwardBase):
    """Cross-channel LRN (znicz LRNormalizerForward surface: ``alpha``,
    ``beta``, ``n`` window size, ``k`` bias; AlexNet-paper defaults)."""

    PARAMS = ()

    def __init__(self, workflow, alpha=1e-4, beta=0.75, n=5, k=2.0,
                 **kwargs):
        super(LRNormalizerForward, self).__init__(workflow, **kwargs)
        self.alpha = float(alpha)
        self.beta = float(beta)
        self.n = int(n)
        self.k = float(k)

    def fill_params(self):
        pass

    def output_shape_for(self, input_shape):
        return input_shape

    def apply(self, params, x):
        sq = x * x
        half = self.n // 2
        # window over the trailing (channel) axis, SAME-style padding
        window = (1,) * (x.ndim - 1) + (self.n,)
        pad = [(0, 0)] * (x.ndim - 1) + [(half, self.n - 1 - half)]
        ssum = jax.lax.reduce_window(
            sq, 0.0, jax.lax.add, window, (1,) * x.ndim, pad)
        return x * jax.lax.pow(self.k + self.alpha * ssum, -self.beta)
