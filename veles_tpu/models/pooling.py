"""Pooling layers (reconstruction of znicz pooling; extras item 1 adds
Depooling for the conv autoencoders).  ``lax.reduce_window`` — XLA lowers
it natively on TPU."""

import jax
import jax.numpy as jnp
import numpy

from veles_tpu.memory import Array
from veles_tpu.models.conv import _pair
from veles_tpu.models.nn_units import ForwardBase


class PoolingBase(ForwardBase):
    """Parameterless window reduction over NHWC."""

    hide_from_registry = True
    PARAMS = ()

    def __init__(self, workflow, kx=2, ky=2, sliding=None, **kwargs):
        super(PoolingBase, self).__init__(workflow, **kwargs)
        self.kx, self.ky = int(kx), int(ky)
        #: user-facing (sliding_x, sliding_y); defaults to the window
        self.sliding = _pair(sliding) if sliding is not None \
            else (self.kx, self.ky)

    def fill_params(self):
        pass

    def export_config(self):
        return {"kx": self.kx, "ky": self.ky,
                "sliding": list(self.sliding)}

    def _window(self):
        return (1, self.ky, self.kx, 1)

    def _strides(self):
        sx, sy = self.sliding
        return (1, sy, sx, 1)

    def output_shape_for(self, input_shape):
        out = jax.eval_shape(
            lambda x: self.apply({}, x),
            jax.ShapeDtypeStruct(input_shape, jnp.float32))
        return out.shape


class MaxPooling(PoolingBase):
    """znicz MaxPooling (stores ``input_offset`` argmax positions in the
    reference for backprop; autodiff makes that bookkeeping implicit)."""

    def apply(self, params, x):
        return jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max,
            self._window(), self._strides(), "VALID")


class AvgPooling(PoolingBase):
    """znicz AvgPooling."""

    def apply(self, params, x):
        summed = jax.lax.reduce_window(
            x, 0.0, jax.lax.add,
            self._window(), self._strides(), "VALID")
        return summed / (self.kx * self.ky)


class Depooling(PoolingBase):
    """Nearest-neighbour upsampling inverse of pooling (znicz depooling,
    extras item 1)."""

    def apply(self, params, x):
        sx, sy = self.sliding
        y = jnp.repeat(x, sy, axis=1)   # H
        return jnp.repeat(y, sx, axis=2)  # W
