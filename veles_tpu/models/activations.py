"""Activation function registry (reconstruction of znicz activation
units, surface per manualrst_veles_algorithms.rst "Activation function
customization (like SinCos activation function)").

Every activation is a pure jax function usable inside any traced step;
:class:`Activation` wraps one as a standalone forward unit for graphs
that insert explicit activation nodes.
"""

import jax.numpy as jnp
import numpy

from veles_tpu.accelerated_units import AcceleratedUnit
from veles_tpu.memory import Array
from veles_tpu.units import MissingDemand


def linear(x):
    return x


def tanh(x):
    # znicz used the LeCun-scaled tanh: 1.7159 * tanh(2/3 x)
    return 1.7159 * jnp.tanh(0.6666 * x)


def relu(x):
    # znicz "relu" was log(1 + exp(x)) (softplus); strict_relu is max(0,x).
    # logaddexp is the overflow-safe form (log1p(exp(88.)) is inf in f32)
    return jnp.logaddexp(x, 0.0)


def strict_relu(x):
    return jnp.maximum(x, 0)


def sigmoid(x):
    return 1.0 / (1.0 + jnp.exp(-x))


def sincos(x):
    """Even feature indices get sin, odd get cos."""
    idx = jnp.arange(x.shape[-1])
    return jnp.where(idx % 2 == 0, jnp.sin(x), jnp.cos(x))


ACTIVATIONS = {
    "linear": linear,
    "tanh": tanh,
    "relu": relu,
    "strict_relu": strict_relu,
    "sigmoid": sigmoid,
    "sincos": sincos,
}


def get_activation(name):
    if callable(name):
        return name
    try:
        return ACTIVATIONS[name]
    except KeyError:
        raise KeyError("unknown activation %r (have: %s)"
                       % (name, sorted(ACTIVATIONS)))


class Activation(AcceleratedUnit):
    """Standalone activation node."""

    READS = ("input",)
    WRITES = ("output",)

    def __init__(self, workflow, activation="linear", **kwargs):
        super(Activation, self).__init__(workflow, **kwargs)
        self.activation = activation
        self.input = None
        self.output = Array()
        self.demand("input")

    def initialize(self, device=None, **kwargs):
        if not isinstance(self.input, Array) or not bool(self.input):
            raise MissingDemand(self, {"input"})
        self.output.reset(numpy.zeros(self.input.shape,
                                      self.input.dtype))
        super(Activation, self).initialize(device=device, **kwargs)

    def step(self, input):
        return {"output": get_activation(self.activation)(input)}
