"""ForwardBase — common machinery of parameterized forward layers.

Reconstruction of znicz ``nn_units.Forward`` (source absent; surface per
manualrst_veles_algorithms.rst): parameters (weights/bias) with "smart
automatic initial filling", per-layer hyper-parameter overrides (extras
item 13: learning rate / weights decay / momentum per layer), and the
pure ``apply`` used both by the in-graph forward step and by the trainer's
fused autodiff program.
"""

import numpy

from veles_tpu import prng as prng_mod
from veles_tpu.accelerated_units import AcceleratedUnit
from veles_tpu.memory import Array
from veles_tpu.units import MissingDemand

#: per-layer hyper-parameters a trainer consults; None = inherit the
#: trainer's global value (surface: znicz kwargs of the same names)
HYPERPARAMS = ("learning_rate", "learning_rate_bias", "weights_decay",
               "weights_decay_bias", "l1_vs_l2", "gradient_moment",
               "gradient_moment_bias")


class ForwardBase(AcceleratedUnit):
    """A layer with trainable params (ref role: znicz nn_units.Forward).

    Subclasses define ``PARAMS`` (names of trainable Arrays), implement
    :meth:`apply(params, x)` as a pure function and
    :meth:`fill_params()` for initialization.
    """

    hide_from_registry = True
    VIEW_GROUP = "WORKER"
    PARAMS = ("weights", "bias")

    def __init__(self, workflow, weights_filling="uniform",
                 weights_stddev=None, bias_filling="uniform",
                 bias_stddev=None, include_bias=True, prng_key="default",
                 **kwargs):
        self.input = None
        self.output = Array()
        super(ForwardBase, self).__init__(workflow, **kwargs)
        self.weights_filling = weights_filling
        self.weights_stddev = weights_stddev
        self.bias_filling = bias_filling
        self.bias_stddev = bias_stddev
        self.include_bias = include_bias
        #: recompute this unit's forward during backward instead of
        #: saving its internal activations (``jax.checkpoint``) — a
        #: transformer block on long sequences would otherwise pin its
        #: [seq, seq] attention tensors across the whole backward pass;
        #: rematerializing trades those HBM bytes for extra MXU FLOPs
        self.remat = bool(kwargs.get("remat", False))
        self.prng = prng_mod.get(prng_key)
        self.weights = Array()
        self.bias = Array()
        for h in HYPERPARAMS:
            setattr(self, h, kwargs.get(h))
        self.demand("input")

    # -- contract -------------------------------------------------------------

    @property
    def reads(self):
        return ("input",) + tuple(self.PARAMS)

    WRITES = ("output",)

    def apply(self, params, x):
        """Pure forward: params is {name: jax array}."""
        raise NotImplementedError()

    def output_shape_for(self, input_shape):
        raise NotImplementedError()

    def fill_params(self):
        """Allocate + smart-fill params given self.input's shape."""
        raise NotImplementedError()

    # -- helpers ---------------------------------------------------------------

    def _fill(self, arr, filling, stddev, fan_in, fan_out):
        """Smart automatic weights/bias filling (extras item 12): scaled
        uniform (Glorot) or gaussian; explicit stddev overrides."""
        if stddev is None:
            stddev = numpy.sqrt(6.0 / (fan_in + fan_out))
        if filling == "uniform":
            self.prng.fill(arr, -stddev, stddev)
        elif filling in ("gaussian", "normal"):
            self.prng.fill_normal(arr, 0.0, stddev)
        elif filling == "constant":
            arr[...] = stddev
        else:
            raise ValueError("unknown filling %r" % filling)

    def param_arrays(self):
        return {name: getattr(self, name) for name in self.PARAMS
                if bool(getattr(self, name))}

    def _export_activation(self):
        """Activation name for export_config — callables can't ride a
        JSON manifest."""
        if callable(self.activation):
            raise ValueError(
                "%s: callable activations cannot be exported — register "
                "a named activation instead" % self)
        return self.activation

    def hyperparams(self):
        """Per-layer overrides, Nones meaning 'inherit'."""
        return {h: getattr(self, h) for h in HYPERPARAMS}

    # -- lifecycle -------------------------------------------------------------

    def initialize(self, device=None, **kwargs):
        if not isinstance(self.input, Array) or not bool(self.input):
            raise MissingDemand(self, {"input"})
        # fill only when NO param is populated (i.e. not restored from a
        # snapshot) — checked across PARAMS, not just "weights", so units
        # with custom param sets (e.g. attention's wq/wk/wv/wo) keep
        # their restored values too
        if not any(bool(getattr(self, p)) for p in self.PARAMS):
            self.fill_params()
        out_shape = self.output_shape_for(self.input.shape)
        self.output.reset(numpy.zeros(out_shape, numpy.float32))
        super(ForwardBase, self).initialize(device=device, **kwargs)

    def step(self, input, **params):
        return {"output": self.apply(params, input)}

    def export_config(self):
        cfg = {"weights_filling": self.weights_filling,
               "include_bias": self.include_bias}
        return cfg
