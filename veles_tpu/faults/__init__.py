"""Deterministic fault injection — the registry tier-1 drives the
fault-tolerance machinery with.

Veles's DCN contract (PAPER.md: the master re-distributes work on
worker loss) only stays honored if every failure path is *exercised*;
waiting for real failures exercises none of them.  This package plants
named **injection points** through the serving scheduler, the REST
endpoint and the coordinator/worker pair; each point is a no-op until
a matching :class:`FaultSpec` is armed, at which moment the point
deterministically misbehaves:

=============  =========================================================
action         behavior at the injection point
=============  =========================================================
``delay``      sleep ``arg`` seconds (default 0.05) — a slow step/frame
``exception``  raise :class:`InjectedFault` — a crashing step/handler
``hang``       sleep ``arg`` seconds (default 3600) — a stuck step the
               watchdogs must detect; tests arm finite hangs so the
               victim eventually *recovers* and cleanup can be asserted
``drop``       :func:`fire` returns True — the caller discards its unit
               of work (a frame, a heartbeat, a reply)
``http_error`` raise :class:`InjectedHTTPError` carrying status code
               ``arg`` (default 500) — REST/router points catch it and
               answer a STRUCTURED JSON error reply instead of
               crashing the handler (a replica that *replies* 500/503
               is a different failure than one that dies mid-socket)
``kill``       ``os._exit(17)`` — sudden process death (real multi-
               process failover drills only; in-process tests prefer
               ``hang`` + heartbeat ``drop``)
=============  =========================================================

Specs carry three modifiers: ``after=N`` skips the first N hits (arm
the 3rd decode step, not the 1st), ``times=M`` disarms after M firings
(a transient fault), and ``key=PATTERN`` scopes the spec to one
caller (e.g. one worker id) when several share a point.  Points and
keys match with :mod:`fnmatch` wildcards — the patterns live in the
SPEC, the literal names at the call site.  Point globs arm whole
subsystems, key globs pick victims within one point::

    serving.scheduler.*=delay:0.01      # every scheduler hazard site
    router.*=exception                  # router forward AND health poll
    router.forward=http_error:503~r2    # only forwards to replica "r2"
    coordinator.worker.heartbeat=drop~w[01]   # workers w0 and w1 only

A key given to :func:`fire` never widens a spec without one: a spec
with no ``~key`` matches every caller of its point, while a keyed
spec matches only callers whose key fits the pattern.

Arming happens through :func:`inject` (tests), :func:`load` (a spec
string), the ``VELES_FAULTS`` environment variable, or
``root.common.faults.spec`` — the latter two parsed once on first
:func:`fire`.  Spec-string grammar, clauses separated by ``;``::

    point=action[:arg][@after][xtimes][~key]
    VELES_FAULTS="serving.scheduler.step=hang:1.5@3x1;restful.generate=delay:0.01"

Every firing increments ``veles_faults_injected_total{point,action}``
and lands in the JSONL event ring, so a soak run's injected faults are
auditable next to the failures they provoked.

:func:`fire` is safe from any thread; an unarmed registry costs one
uncontended lock acquisition per call.
"""

import fnmatch
import os
import threading
import time

__all__ = ("InjectedFault", "InjectedHTTPError", "FaultSpec",
           "inject", "load", "clear", "active", "fire")

ACTIONS = ("delay", "exception", "hang", "drop", "http_error", "kill")


class InjectedFault(Exception):
    """Raised at an ``exception``-armed injection point."""


class InjectedHTTPError(InjectedFault):
    """Raised at an ``http_error``-armed point: REST/router handlers
    catch it and reply a structured JSON error with :attr:`status`."""

    def __init__(self, status=500):
        self.status = int(status)
        super(InjectedHTTPError, self).__init__(
            "injected HTTP %d" % self.status)


class FaultSpec:
    """One armed fault: where (``point``/``key`` patterns), what
    (``action`` + ``arg``), and when (``after``/``times``)."""

    __slots__ = ("point", "action", "arg", "after", "times", "key",
                 "hits", "fired")

    def __init__(self, point, action, arg=None, after=0, times=None,
                 key=None):
        if action not in ACTIONS:
            raise ValueError("unknown fault action %r (one of %s)"
                             % (action, ", ".join(ACTIONS)))
        self.point = str(point)
        self.action = action
        self.arg = arg
        self.after = int(after)
        self.times = None if times is None else int(times)
        self.key = key
        self.hits = 0
        self.fired = 0

    def matches(self, point, key):
        if not fnmatch.fnmatchcase(point, self.point):
            return False
        if self.key is None:
            return True
        return key is not None and fnmatch.fnmatchcase(str(key),
                                                       self.key)

    def __repr__(self):
        return "<fault %s=%s arg=%r after=%d times=%r key=%r " \
            "fired=%d>" % (self.point, self.action, self.arg,
                           self.after, self.times, self.key,
                           self.fired)


_lock = threading.Lock()
_specs = []
_env_loaded = False


def _metric():
    from veles_tpu.telemetry import metrics
    return metrics.counter(
        "veles_faults_injected_total",
        "fault injections fired, by point and action",
        labelnames=("point", "action"))


def _parse_clause(clause):
    """``point=action[:arg][@after][xtimes][~key]`` → FaultSpec."""
    point, sep, rest = clause.partition("=")
    if not sep or not point.strip():
        raise ValueError("fault clause %r is not point=action[...]"
                         % clause)
    rest, _, key = rest.partition("~")
    key = key.strip() or None
    times = None
    if "x" in rest:
        rest, _, t = rest.rpartition("x")
        times = int(t)
    after = 0
    if "@" in rest:
        rest, _, a = rest.rpartition("@")
        after = int(a)
    action, _, arg = rest.partition(":")
    return FaultSpec(point.strip(), action.strip(),
                     arg=float(arg) if arg else None,
                     after=after, times=times, key=key)


def load(spec):
    """Arm every ``;``-separated clause of a spec string (the
    ``VELES_FAULTS`` / ``root.common.faults.spec`` grammar)."""
    armed = []
    for clause in (spec or "").split(";"):
        clause = clause.strip()
        if clause:
            armed.append(_parse_clause(clause))
    with _lock:
        _specs.extend(armed)
    return armed


def _load_env_locked():
    global _env_loaded
    if _env_loaded:
        return
    _env_loaded = True  # latch FIRST: a bad spec must not re-raise per fire
    spec = os.environ.get("VELES_FAULTS", "")
    if not spec:
        try:
            from veles_tpu.config import root
            spec = root.common.faults.get("spec", "") or ""
        except Exception:
            spec = ""
    for clause in spec.split(";"):
        clause = clause.strip()
        if clause:
            _specs.append(_parse_clause(clause))


def inject(point, action, arg=None, after=0, times=None, key=None):
    """Arm one fault programmatically; returns the spec handle."""
    spec = FaultSpec(point, action, arg=arg, after=after, times=times,
                     key=key)
    with _lock:
        _specs.append(spec)
    return spec


def clear(point=None):
    """Disarm everything (or only specs whose point pattern equals
    ``point``).  Tests call this in teardown."""
    with _lock:
        if point is None:
            del _specs[:]
        else:
            _specs[:] = [s for s in _specs if s.point != point]


def active():
    """Snapshot of armed specs (operator/debug introspection)."""
    with _lock:
        _load_env_locked()
        return list(_specs)


def fire(point, key=None):
    """The injection point: call at a hazard site; returns True when
    an armed ``drop`` spec says to discard this unit of work.  May
    sleep (``delay``/``hang``), raise :class:`InjectedFault`
    (``exception``) or end the process (``kill``)."""
    with _lock:
        _load_env_locked()
        if not _specs:
            return False
        due = []
        for s in _specs:
            if not s.matches(point, key):
                continue
            s.hits += 1
            if s.hits <= s.after:
                continue
            if s.times is not None and s.fired >= s.times:
                continue
            s.fired += 1
            due.append(s)
    drop = False
    for s in due:  # sleeps/raises happen OUTSIDE the registry lock
        _metric().labels(point=point, action=s.action).inc()
        from veles_tpu.logger import events
        events.record("fault.injected", "single", cls="faults",
                      point=point, action=s.action, key=key,
                      arg=s.arg)
        if s.action == "delay":
            time.sleep(float(s.arg if s.arg is not None else 0.05))
        elif s.action == "hang":
            time.sleep(float(s.arg if s.arg is not None else 3600.0))
        elif s.action == "exception":
            raise InjectedFault("injected fault at %s" % point)
        elif s.action == "http_error":
            raise InjectedHTTPError(int(s.arg) if s.arg else 500)
        elif s.action == "drop":
            drop = True
        elif s.action == "kill":
            os._exit(17)
    return drop
