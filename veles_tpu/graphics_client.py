"""Graphics client — the matplotlib process.

Rebuild of veles/graphics_client.py:84 + plotter renderers: subscribes
to the training process's PUB endpoint, renders every payload kind with
matplotlib (Agg by default — PNG files per plot name; the reference's
Qt/WebAgg interactive modes map to matplotlib backend selection), and
exits when the publisher disappears.

Run:  ``python -m veles_tpu.graphics_client tcp://127.0.0.1:PORT
--out plots/``
"""

import argparse
import gzip
import os
import sys

import numpy

from veles_tpu.safe_pickle import safe_loads


def render_payload(payload, figure=None):
    """payload dict → matplotlib Figure (the renderer registry;
    ref: plotting_units draw methods)."""
    import matplotlib
    matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt
    fig = figure or plt.figure(figsize=(6, 4))
    fig.clf()
    ax = fig.add_subplot(111)
    kind = payload["kind"]
    if kind == "curve":
        for label, ys in payload["series"].items():
            ax.plot(ys, label=label)
        ax.set_xlabel("updates")
        ax.set_ylabel(payload.get("ylabel", "value"))
        ax.legend(loc="best")
    elif kind == "matrix":
        data = numpy.asarray(payload["data"])
        im = ax.imshow(data, interpolation="nearest", cmap="viridis")
        fig.colorbar(im, ax=ax)
        ax.set_xlabel("predicted")
        ax.set_ylabel("target")
    elif kind == "images":
        tiles = numpy.asarray(payload["tiles"])
        n = len(tiles)
        side = int(numpy.ceil(numpy.sqrt(n)))
        fig.clf()
        for i, tile in enumerate(tiles):
            sub = fig.add_subplot(side, side, i + 1)
            sub.imshow(tile, cmap="gray")
            sub.axis("off")
    elif kind == "histogram":
        edges = payload["edges"]
        ax.bar(edges[:-1], payload["counts"],
               width=numpy.diff(edges), align="edge")
    elif kind == "multi_histogram":
        fig.clf()
        layers = payload["layers"]
        for i, (name, h) in enumerate(sorted(layers.items())):
            sub = fig.add_subplot(len(layers), 1, i + 1)
            edges = h["edges"]
            sub.bar(edges[:-1], h["counts"],
                    width=numpy.diff(edges), align="edge")
            sub.set_title(name, fontsize=8)
    elif kind == "table":
        ax.axis("off")
        ax.table(cellText=[[str(c) for c in row]
                           for row in payload["rows"]],
                 colLabels=payload["header"], loc="center")
    else:
        raise ValueError("unknown payload kind %r" % kind)
    fig.suptitle(payload.get("name", kind))
    return fig


def main(argv=None):
    p = argparse.ArgumentParser(prog="veles_tpu.graphics_client")
    p.add_argument("endpoint", help="PUB endpoint, e.g. tcp://host:port")
    p.add_argument("--out", default="plots", help="PNG output directory")
    p.add_argument("--timeout", type=float, default=30.0,
                   help="exit after this many idle seconds")
    p.add_argument("--limit", type=int, default=0,
                   help="exit after N payloads (0 = run until idle)")
    args = p.parse_args(argv)
    import zmq
    ctx = zmq.Context.instance()
    sock = ctx.socket(zmq.SUB)
    sock.setsockopt(zmq.SUBSCRIBE, b"")
    sock.connect(args.endpoint)
    os.makedirs(args.out, exist_ok=True)
    n = 0
    poller = zmq.Poller()
    poller.register(sock, zmq.POLLIN)
    fig = None  # one figure reused across payloads (no pyplot leak)
    while True:
        if not poller.poll(args.timeout * 1000):
            break
        payload = safe_loads(gzip.decompress(sock.recv()))
        fig = render_payload(payload, figure=fig)
        path = os.path.join(
            args.out, "%s.png" % payload.get("name", "plot"))
        fig.savefig(path)
        print("rendered %s -> %s" % (payload["kind"], path), flush=True)
        n += 1
        if args.limit and n >= args.limit:
            break
    return 0


if __name__ == "__main__":
    sys.exit(main())
