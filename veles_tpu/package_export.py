"""Model package export + loading — the L10 interchange format.

Rebuild of ``Workflow.package_export`` (ref: veles/workflow.py:868-975,
archive of contents.json + .npy arrays) and the loader side of libVeles
(ref: libVeles/src/workflow_loader.cc:41-131, unit_factory.cc:1-65).

Archive layout (``.tar.gz``)::

    contents.json     manifest: workflow name/checksum, unit list
                      (class + stable UUID + config + param refs),
                      input spec
    u<i>_<param>.npy  one npy per parameter
    forward.shlo      jax.export StableHLO of the full forward chain
                      (signature: fn(params_flat..., x) -> logits)

Consumers:

- :func:`load_package` (this module) — "python" mode re-instantiates
  the forward units from the UUID factory (no original workflow module
  needed) and runs ``apply`` chains; "stablehlo" mode executes the
  serialized program byte-for-byte as exported.
- ``runtime/`` — the C++ inference runner parses the same archive with
  its own npy/json/tar readers and executes natively.

**Format compatibility:** this is a deliberately NEW format, not the
reference's.  libVeles archives use ``units[i].class.{name,uuid}``
nesting, a ``links`` graph, ``@NNNN_shape`` array references and zip by
default (veles/workflow.py:868-975); this exporter writes a flat
unit list, ``u<i>_<name>.npy`` files and tar.gz, and adds the
StableHLO program libVeles never had.  Reference libVeles tooling
cannot load these archives (and vice versa) — the ``"veles_tpu"``
``format`` key in contents.json marks the difference explicitly.
"""

import io
import json
import os
import tarfile

import numpy

#: the highest format this runtime understands.  Writers stamp the
#: LOWEST version whose features a package actually uses (V2_KEYS),
#: so plain packages stay loadable by older deployments.
FORMAT_VERSION = 2
#: unit-config keys that require a v2 reader
V2_KEYS = ("block_size", "attn_block_size", "space_to_depth")


def _unit_entry(i, unit):
    from veles_tpu.mutable import unshadow
    cls = unshadow(type(unit))
    params, blobs = {}, {}
    for name, arr in unit.param_arrays().items():
        fname = "u%d_%s.npy" % (i, name)
        params[name] = fname
        blobs[fname] = numpy.asarray(arr.map_read().mem)
    return {
        "name": unit.name,
        "class": cls.__name__,
        "uuid": cls.__id__,
        "config": unit.export_config(),
        "params": params,
    }, blobs


def _export_stablehlo(forwards, input_shape, input_dtype):
    """Serialize the forward chain as one StableHLO program
    ``fn(params_pytree, x)`` via jax.export."""
    import jax
    from jax import export as jax_export

    def forward(params, x):
        h = x
        for i, u in enumerate(forwards):
            h = u.apply(params.get(str(i), {}), h)
        return h

    params_spec = {
        str(i): {name: jax.ShapeDtypeStruct(arr.shape, arr.mem.dtype)
                 for name, arr in u.param_arrays().items()}
        for i, u in enumerate(forwards)}
    x_spec = jax.ShapeDtypeStruct(tuple(input_shape), input_dtype)
    exported = jax_export.export(jax.jit(forward))(params_spec, x_spec)
    return exported.serialize()


def export_package(forwards, path, input_shape, input_dtype=numpy.float32,
                   name="workflow", checksum=""):
    """Write the package archive for a forward chain.

    ``input_shape[0]`` (batch) is baked static — the runner pads inputs
    to it, the same static-shape discipline the framework uses on TPU.
    """
    manifest = {
        "format": "veles_tpu",  # NOT libVeles-compatible (see module doc)
        "format_version": 1,  # raised below if v2 features are present
        "workflow": name,
        "checksum": checksum,
        "input": {"shape": list(input_shape),
                  "dtype": numpy.dtype(input_dtype).name},
        "units": [],
        "stablehlo": "forward.shlo",
    }
    blobs = {}
    for i, u in enumerate(forwards):
        entry, params = _unit_entry(i, u)
        manifest["units"].append(entry)
        blobs.update(params)
        if any(k in entry["config"] for k in V2_KEYS):
            manifest["format_version"] = 2
    try:
        shlo = _export_stablehlo(forwards, input_shape, input_dtype)
    except Exception as e:  # pragma: no cover - jax.export availability
        import logging
        logging.getLogger("package_export").warning(
            "StableHLO export unavailable (%s); package will carry "
            "weights + config only", e)
        shlo = None
        manifest["stablehlo"] = None

    with tarfile.open(path, "w:gz") as tar:
        def add_bytes(fname, data):
            info = tarfile.TarInfo(fname)
            info.size = len(data)
            tar.addfile(info, io.BytesIO(data))

        add_bytes("contents.json",
                  json.dumps(manifest, indent=1).encode())
        for fname, arr in blobs.items():
            buf = io.BytesIO()
            numpy.save(buf, arr)
            add_bytes(fname, buf.getvalue())
        if shlo is not None:
            add_bytes("forward.shlo", bytes(shlo))
    return path


class PackagedWorkflow:
    """A loaded package: runs the forward chain on new inputs
    (ref role: libVeles Workflow, libVeles/inc/veles/workflow.h)."""

    def __init__(self, manifest, params, units, exported):
        self.manifest = manifest
        self.params = params      # {str(i): {name: numpy}}
        self.units = units        # re-instantiated forward units
        self._exported = exported

    @property
    def input_shape(self):
        return tuple(self.manifest["input"]["shape"])

    def _pad_batch(self, x):
        batch = self.input_shape[0]
        if x.shape[0] > batch:
            raise ValueError("batch %d exceeds packaged %d"
                             % (x.shape[0], batch))
        if x.shape[0] < batch:
            pad = numpy.zeros((batch - x.shape[0],) + x.shape[1:],
                              x.dtype)
            return numpy.concatenate([x, pad]), x.shape[0]
        return x, x.shape[0]

    def run(self, x, mode="python"):
        """Forward pass; ``mode`` = "python" (unit chain) or "stablehlo"
        (the serialized program, bit-identical to export time)."""
        import jax.numpy as jnp
        x = numpy.asarray(x, self.manifest["input"]["dtype"])
        squeeze = x.ndim == len(self.input_shape) - 1
        if squeeze:
            x = x[None]
        x, n = self._pad_batch(x)
        if mode == "stablehlo":
            if self._exported is None:
                raise RuntimeError("package carries no StableHLO")
            y = self._exported.call(
                {i: {k: jnp.asarray(v) for k, v in p.items()}
                 for i, p in self.params.items()}, jnp.asarray(x))
        else:
            h = jnp.asarray(x)
            for i, u in enumerate(self.units):
                p = {k: jnp.asarray(v)
                     for k, v in self.params.get(str(i), {}).items()}
                h = u.apply(p, h)
            y = h
        y = numpy.asarray(y)[:n]
        return y[0] if squeeze else y


def load_package(path):
    """Load an archive into a :class:`PackagedWorkflow`
    (ref: libVeles WorkflowLoader::Load, workflow_loader.cc:41-47)."""
    from veles_tpu.unit_registry import UnitRegistry
    import veles_tpu.models  # noqa: F401 — populates the unit registry

    with tarfile.open(path, "r:gz") as tar:
        files = {m.name: tar.extractfile(m).read()
                 for m in tar.getmembers() if m.isfile()}
    manifest = json.loads(files["contents.json"])
    if manifest["format_version"] > FORMAT_VERSION:
        raise ValueError("package format %s is newer than this runtime"
                         % manifest["format_version"])
    params, units = {}, []
    for i, entry in enumerate(manifest["units"]):
        cls = UnitRegistry.by_id.get(entry["uuid"])
        if cls is None:  # renamed class: fall back to class-name lookup
            cls = UnitRegistry.units.get(entry["class"])
        if cls is None:
            raise KeyError("no unit class for %s (%s)"
                           % (entry["class"], entry["uuid"]))
        unit = cls(None, name=entry["name"], **entry["config"])
        units.append(unit)
        params[str(i)] = {
            name: numpy.load(io.BytesIO(files[fname]))
            for name, fname in entry["params"].items()}
    exported = None
    if manifest.get("stablehlo") and manifest["stablehlo"] in files:
        try:
            from jax import export as jax_export
            exported = jax_export.deserialize(files[manifest["stablehlo"]])
        except Exception:  # pragma: no cover
            exported = None
    return PackagedWorkflow(manifest, params, units, exported)
