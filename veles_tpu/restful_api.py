"""REST inference serving (rebuild of veles/restful_api.py:78 +
loader/restful.py:52).

``RestfulLoader`` queues HTTP request payloads as minibatches;
``RESTfulAPI`` owns the HTTP endpoint (stdlib threading server — the
reference used twisted.web) and completes each pending request with the
forward chain's output for its row.  Graph shape::

    start → repeater → restful_loader → [forwards] → api ─→ repeater
                                         (loop until the feed closes)
"""

import concurrent.futures
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy

from veles_tpu import faults
from veles_tpu.loader.interactive import InteractiveLoader
from veles_tpu.memory import Array
from veles_tpu.telemetry import reqtrace
from veles_tpu.units import Unit


def _status_text(e):
    """Exception → HTTP status-line-safe text: whitespace (incl. the
    newlines of multi-line JAX errors) collapsed to spaces — a raw
    newline would split the status line (header injection) — and
    latin-1 only (send_response_only encodes strict), 200 chars."""
    line = " ".join(str(e).split())[:200] or type(e).__name__
    return line.encode("latin-1", "replace").decode("latin-1")


class RestfulLoader(InteractiveLoader):
    """Interactive loader whose samples carry reply futures
    (ref: veles/loader/restful.py:52)."""

    def init_unpickled(self):
        super(RestfulLoader, self).init_unpickled()
        self._fifo_ = []
        self._feed_lock_ = threading.Lock()
        self.pending_futures_ = []

    def feed_request(self, sample):
        # validate BEFORE registering the future, and register+enqueue
        # atomically — concurrent HTTP threads must keep the reply FIFO
        # aligned with the sample queue, and a rejected sample must not
        # leave an orphan future shifting every later reply
        sample = numpy.asarray(sample, numpy.float32)
        if sample.shape != self.sample_shape:
            raise ValueError("sample shape %s != %s"
                             % (sample.shape, self.sample_shape))
        future = concurrent.futures.Future()
        with self._feed_lock_:
            self._fifo_.append(future)
            self.feed(sample)
        return future

    def run(self):
        super(RestfulLoader, self).run()
        # the futures for exactly the rows just served, in row order
        self.pending_futures_ = self._fifo_[:self.minibatch_size]
        del self._fifo_[:self.minibatch_size]


class RESTfulAPI(Unit):
    """HTTP endpoint unit (ref: veles/restful_api.py:78): POST /api
    ``{"input": [...]}`` → ``{"result": [...]}``.  Runs after the
    forward chain; resolves each request's future with its output row.

    With an LM ``forwards`` chain, POST /generate serves through the
    continuous-batching scheduler (``veles_tpu/serving/``): each
    prompt row is an independent request that joins a decode slot at a
    token boundary, so concurrent clients genuinely interleave — there
    is no decode lock on this path.  Admission control surfaces as
    HTTP 503 (queue full) / 408 (queue deadline), and GET
    /serving/metrics reports TTFT, throughput, queue depth, slot
    occupancy and free/used KV blocks — the memory-pressure headroom
    that predicts admission stalls under the paged cache.  Beam
    requests (and chains the scheduler cannot serve) fall back to the
    serialized legacy decode.
    """

    VIEW_GROUP = "SERVICE"

    def __init__(self, workflow, loader=None, port=0, host="127.0.0.1",
                 request_timeout=30.0, forwards=None, serving=True,
                 max_slots=4, serving_window=None, max_queue=32,
                 max_steps=None, max_batch=None, serving_kv=None,
                 serving_block_size=None, serving_kv_blocks=None,
                 serving_kv_dtype=None, serving_prefill_chunk=None,
                 serving_spec=None, serving_spec_k=None,
                 serving_prefix_cache=None, serving_warm_buckets=None,
                 serving_tp=None, serving_role=None,
                 serving_kv_host_bytes=None,
                 serving_kv_export_bytes=None,
                 replica_id=None, **kwargs):
        super(RESTfulAPI, self).__init__(workflow, **kwargs)
        self.loader = loader
        #: fleet identity: every reply carries it as X-Veles-Replica
        #: so a fronting router (serving/router.py) can attribute
        #: responses; defaults to pid:port once the server binds
        self.replica_id = replica_id
        self.output = None  # linked from the head forward unit
        self.port = port
        self.host = host
        self.request_timeout = request_timeout
        #: optional callable fired by POST /shutdown (serving workflows
        #: wire their stop request here)
        self.shutdown_callback = None
        #: optional LM forward chain (… → TokenProjection); when set,
        #: POST /generate decodes autoregressively via the serving
        #: scheduler (or models/generate when serving is off)
        self.forwards = forwards
        #: continuous-batching knobs (serving=False pins the legacy
        #: serialized decode path)
        self.serving = bool(serving)
        self.max_slots = int(max_slots)
        self.serving_window = serving_window
        self.max_queue = int(max_queue)
        #: paged-KV / chunked-prefill knobs (None defers to
        #: ``root.common.serving.*`` — see serving/scheduler.py)
        self.serving_kv = serving_kv
        self.serving_block_size = serving_block_size
        self.serving_kv_blocks = serving_kv_blocks
        #: KV pool storage dtype ("fp32"/"int8"; None defers to
        #: ``root.common.serving.kv_dtype``) — int8 roughly doubles
        #: concurrent streams per HBM budget, quality-gated
        self.serving_kv_dtype = serving_kv_dtype
        self.serving_prefill_chunk = serving_prefill_chunk
        #: speculative decoding / radix prefix cache (None defers to
        #: ``root.common.serving.{spec,spec_k,prefix_cache}``)
        self.serving_spec = serving_spec
        self.serving_spec_k = serving_spec_k
        self.serving_prefix_cache = serving_prefix_cache
        #: None defers to root.common.serving.warm_buckets; tests pin
        #: False (the bucket-ladder warmup is the compile hog)
        self.serving_warm_buckets = serving_warm_buckets
        #: tensor-parallel mesh size (None defers to
        #: ``root.common.serving.tp``; 0 = unsharded) — shards the
        #: jitted serving steps so weights + paged pools split over
        #: N chips (serving/tp.py)
        self.serving_tp = serving_tp
        #: disaggregation role (None defers to
        #: ``root.common.serving.role``): "prefill" replicas serve
        #: POST /serving/prefill + GET /serving/kv_export/<handle>
        #: only; "decode" replicas adopt exports via POST
        #: /serving/kv_import; "both" (default) is colocated
        self.serving_role = serving_role
        #: tiered-KV knobs (None defers to
        #: ``root.common.serving.{kv_host_bytes,kv_export_bytes}``):
        #: host-RAM overflow budget for evicted prefix blocks, and
        #: the byte cap on outstanding disagg KV exports
        self.serving_kv_host_bytes = serving_kv_host_bytes
        self.serving_kv_export_bytes = serving_kv_export_bytes
        #: /generate resource caps — an unbounded request would pay a
        #: giant alloc + a multi-second compile before failing; None
        #: defers to root.common.api.{max_steps,max_batch}
        self.max_steps = max_steps
        self.max_batch = max_batch
        self.demand("loader", "output")

    def _cap(self, name, default):
        """Resolve a /generate resource cap: constructor override,
        else ``root.common.api.<name>``, else the built-in default —
        read per request so ``-c`` overrides apply live."""
        value = getattr(self, name)
        if value is None:
            from veles_tpu.config import root
            value = root.common.api.get(name, default)
        return int(value)

    def _validate_prompt(self, prompt):
        """Reject malformed /generate prompts with a client error
        (the decode would otherwise return 200 with tokens conditioned
        on a phantom zero row, or gather a clamped wrong embedding)."""
        if prompt.ndim != 2 or prompt.shape[1] < 1 or not prompt.size:
            return "prompt must be a non-empty token list (or a " \
                   "batch of non-empty lists — ragged is fine)"
        vocab = getattr(self.forwards[0], "vocab", None)
        if vocab is not None and \
                (prompt.min() < 0 or prompt.max() >= int(vocab)):
            return "prompt token ids must be in [0, %d)" % vocab
        return None

    def _validate_rows(self, rows):
        """Vocab-bounds check for parsed token rows (the /v1 paths,
        which skip the numpy padding _validate_prompt works on)."""
        vocab = getattr(self.forwards[0], "vocab", None)
        if vocab is not None:
            for r in rows:
                if min(r) < 0 or max(r) >= int(vocab):
                    return "token ids must be in [0, %d)" % vocab
        return None

    def _decode_beam(self, prompt, steps, beam):
        """Beam-search decode for /generate (serialized like
        :meth:`_decode` — beam search stays off the slot scheduler)."""
        from veles_tpu.models.generate import generate_beam
        with self._legacy_lock_:
            return generate_beam(self.forwards, prompt, steps, beam)

    def _decode(self, prompt, steps, temperature, top_k, seed,
                prompt_lens=None, stop_token=None):
        """Legacy lockstep decode for /generate — the fallback when
        the serving scheduler is off or cannot serve the chain.
        Serialized: decode requests share the chain's param Arrays and
        the compile caches; a novel (batch, prompt_len, steps,
        sampler) shape compiles a fresh executable on first use
        (seconds), so variable-shape clients pay per shape, cached
        thereafter (ragged lengths within one shape reuse the same
        executable — the lens are a traced argument)."""
        import jax

        from veles_tpu.models.generate import generate, \
            kv_cache_eligible
        if seed is None:
            # an unpinned sampling request must draw FRESH tokens per
            # call — a constant default would replay one "sample"
            import os
            seed = int.from_bytes(os.urandom(4), "little")
        key = jax.random.key(int(seed)) if temperature else None
        with self._legacy_lock_:
            return generate(self.forwards, prompt, steps,
                            temperature=temperature, top_k=top_k,
                            key=key,
                            kv_cache=kv_cache_eligible(self.forwards),
                            prompt_lens=prompt_lens,
                            stop_token=stop_token)

    def _generate_scheduled(self, rows, steps, temperature, top_k,
                            seed, stop, priority=None, trace=None,
                            resume_tokens=None, tenant=None):
        """Decode a /generate body through the continuous-batching
        scheduler: every prompt row is its own request (ragged batches
        interleave in the slots like independent clients).  Returns
        per-row token lists, each ending at its first generated stop
        token.  A pinned seed stays reproducible per row (row i draws
        from seed + i).

        Any failure (a row's scheduler error, a timeout, the handler
        thread dying with its client) CANCELS the batch's unfinished
        futures — an abandoned request must hand its slot and KV
        blocks back at the next decode boundary instead of decoding
        for a client that is gone."""
        futures = []
        try:
            for i, row in enumerate(rows):
                futures.append(self.scheduler_.submit(
                    row, steps, temperature=temperature, top_k=top_k,
                    seed=None if seed is None else int(seed) + i,
                    stop_token=stop, timeout=self.request_timeout,
                    priority=priority, trace=trace,
                    resume_tokens=resume_tokens, tenant=tenant))
            # the scheduler enforces the deadline itself (408 with
            # partial-token count); the result wait is only a backstop
            # against a wedged loop with the watchdog disabled
            return [f.result(self.request_timeout + 30.0)
                    for f in futures]
        except BaseException:
            for f in futures:
                if not f.done():
                    self.scheduler_.cancel(f)
            raise

    def init_unpickled(self):
        super(RESTfulAPI, self).init_unpickled()
        self._server_ = None
        self._thread_ = None
        self._legacy_lock_ = threading.Lock()
        self.scheduler_ = None
        #: replica-tier alert engine (telemetry/alerts.py), created
        #: at initialize() when root.common.alerts.enabled
        self.alerts_ = None
        #: replica-tier history store (telemetry/tsdb.py), created
        #: at initialize() when root.common.tsdb.enabled — samples
        #: the process registry; GET /metrics/history queries it
        self.tsdb_ = None
        #: POST /drain latched: /healthz answers 503 "draining" and
        #: the scheduler (if any) stops admitting
        self._draining_ = False

    def initialize(self, **kwargs):
        super(RESTfulAPI, self).initialize(**kwargs)
        if self.forwards is not None:
            # warm the device params NOW, single-threaded: Array.devmem
            # lazily uploads on first touch and is not thread-safe
            # against the concurrent HTTP handler threads /generate
            # runs on (the upload nulls the buffer before replacing it)
            for u in self.forwards:
                for arr in u.param_arrays().values():
                    arr.devmem
        if self.forwards is not None and self.serving \
                and self.scheduler_ is None:
            from veles_tpu.serving import (
                InferenceScheduler, serving_supported)
            if serving_supported(self.forwards):
                self.scheduler_ = InferenceScheduler(
                    self.forwards, max_slots=self.max_slots,
                    window=self.serving_window,
                    max_queue=self.max_queue,
                    queue_timeout=self.request_timeout,
                    kv=self.serving_kv,
                    block_size=self.serving_block_size,
                    kv_blocks=self.serving_kv_blocks,
                    kv_dtype=self.serving_kv_dtype,
                    prefill_chunk=self.serving_prefill_chunk,
                    spec=self.serving_spec,
                    spec_k=self.serving_spec_k,
                    prefix_cache=self.serving_prefix_cache,
                    warm_buckets=self.serving_warm_buckets,
                    tp=self.serving_tp,
                    role=self.serving_role,
                    kv_host_bytes=self.serving_kv_host_bytes,
                    kv_export_bytes=self.serving_kv_export_bytes,
                    replica_id=self.replica_id).start()
                self.info(
                    "serving scheduler: %d slots, window %d, "
                    "queue cap %d, kv=%s (block %d), prefill "
                    "chunk %d, tp=%d, role=%s",
                    self.scheduler_.max_slots,
                    self.scheduler_.window, self.max_queue,
                    self.scheduler_.kv, self.scheduler_.block_size,
                    self.scheduler_.prefill_chunk,
                    self.scheduler_.tp, self.scheduler_.role)
            else:
                self.info("chain not slot-servable; /generate stays "
                          "on the serialized decode path")
        if self._server_ is not None:
            return
        api = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def _admin_ok(self):
                """Admin endpoints (/drain, /shutdown) are loopback-
                only UNLESS root.common.api.admin_token is set and the
                caller presents it as ``Authorization: Bearer`` — the
                remote-router story; constant-time compare so the
                token is not a timing oracle."""
                peer = self.client_address[0]
                if peer in ("127.0.0.1", "::1", "localhost"):
                    return True
                import hmac
                from veles_tpu.config import root
                token = root.common.api.get("admin_token", None)
                if not token:
                    return False
                auth = self.headers.get("Authorization", "")
                return hmac.compare_digest(auth, "Bearer %s" % token)

            def _trace(self):
                """The request's trace id: the sanitized client
                ``X-Veles-Trace`` header (direct hit or forwarded by
                the router) or a freshly minted edge id — cached per
                request so headers and body frames all carry ONE
                id."""
                tid = getattr(self, "_trace_", None)
                if tid is None:
                    tid = self._trace_ = reqtrace.ensure_trace_id(
                        self.headers.get(reqtrace.TRACE_HEADER))
                return tid

            def _tenant(self):
                """The request's resolved tenant id (cached like the
                trace id): a loopback peer's ``X-Veles-Tenant`` is
                trusted — the router forwards its bounded tenant
                label that way — while a direct remote caller
                resolves from its own bearer token."""
                ten = getattr(self, "_tenant_", None)
                if ten is None:
                    from veles_tpu.tenant import resolve_tenant
                    ten = self._tenant_ = resolve_tenant(
                        {k.lower(): v
                         for k, v in self.headers.items()},
                        loopback=self.client_address[0] in
                        ("127.0.0.1", "::1", "localhost"))
                return ten

            def do_GET(self):
                # drop any query string BEFORE trimming the trailing
                # slash — load-balancer probes send /healthz?probe=1
                self._trace_ = None  # fresh id per request
                self._tenant_ = None
                route = self.path.split("?")[0].rstrip("/")
                if route == "/debug/requests":
                    # the LIVE in-flight request table: trace id,
                    # phase, class, age, tokens, blocks held — the
                    # per-request half /debug/state's aggregates lack
                    sch = api.scheduler_
                    self._reply_json({
                        "replica": api.replica_id,
                        "draining": bool(api._draining_),
                        "requests": sch.debug_requests()
                        if sch is not None else [],
                    })
                    return
                if route == "/serving/metrics":
                    if api.scheduler_ is None:
                        self.send_error(404, "no serving scheduler")
                        return
                    self._reply_json(api.scheduler_.metrics())
                    return
                if route.startswith("/serving/kv_export/"):
                    # disaggregated handoff, the wire half: serve one
                    # parked prefill export (one-shot — the fetch
                    # consumes it; the handle is the capability)
                    if api.scheduler_ is None:
                        self.send_error(404, "no serving scheduler")
                        return
                    from veles_tpu.serving.disagg import encode_export
                    handle = route.rsplit("/", 1)[1]
                    rec = api.scheduler_.kv_export(handle)
                    if rec is None:
                        if api.scheduler_.kv_export_status(handle) \
                                == "fetched":
                            # a double-fetch RACE (two routers, a
                            # retry crossing the original) answers a
                            # structured 409, not a crash or a
                            # misleading 404: the record was served
                            # exactly once and the loser must re-run
                            # prefill, not retry the fetch
                            self._reply_error(
                                409, "kv export handle already "
                                "fetched (one-shot)")
                            return
                        self.send_error(
                            404, "unknown or expired kv export "
                            "handle")
                        return
                    if self._wants_binary():
                        # zero-copy binary framing (Accept:
                        # application/x-veles-kv) — the fast path
                        # both disagg handoffs and peer prefix
                        # fetches negotiate; legacy peers keep the
                        # b64-JSON envelope below
                        from veles_tpu.serving.disagg import \
                            encode_export_binary
                        self._reply_binary(encode_export_binary(rec))
                        return
                    self._reply_json(encode_export(rec))
                    return
                if route == "/healthz":
                    # liveness + health-policy state: 200 while the
                    # model is trainable/servable, 503 once the halt
                    # policy latched (the process stays up for
                    # forensics — load balancers just stop routing)
                    # or once a drain began (rolling restarts: the
                    # router stops sending traffic, in-flight work
                    # finishes)
                    import os
                    from veles_tpu.telemetry.health import monitor
                    state = monitor.state()
                    status = state["status"]
                    # "draining" must stay a DISTINCT top-level string
                    # (plus the boolean): a router parses it to route
                    # the replica as draining, which is NOT a health
                    # failure and must not trip its circuit breaker
                    sch = api.scheduler_
                    reply = {"status": status, "pid": os.getpid(),
                             "replica": api.replica_id,
                             "draining": bool(api._draining_),
                             # role-aware routing reads this: the
                             # router sends prefill traffic only to
                             # prefill/both replicas and client
                             # decode only to decode/both
                             "role": sch.role if sch is not None
                             else "both",
                             "tp": sch.tp if sch is not None else 0,
                             "health": state}
                    if api._draining_:
                        status = reply["status"] = "draining"
                        sch = api.scheduler_
                        reply["in_flight"] = \
                            sch.in_flight if sch is not None else 0
                        reply["drained"] = \
                            sch.drained if sch is not None else True
                    self._reply_json(
                        reply,
                        code=503 if status in ("halted", "draining")
                        else 200)
                    return
                if route == "/debug/state":
                    # flight-recorder tail of the LIVE process: recent
                    # span events + recorder/health state, the same
                    # ingredients a crash bundle would dump
                    from veles_tpu.logger import events
                    from veles_tpu.telemetry.flight_recorder import \
                        recorder
                    from veles_tpu.telemetry.health import monitor
                    self._reply_json({
                        "flightrec": recorder.state(),
                        "health": monitor.state(),
                        "events": list(events.ring)[-100:],
                        "logs": list(recorder.log_ring)[-50:],
                    })
                    return
                if route == "/v1/models":
                    # OpenAI-compatible model listing (ecosystem
                    # clients enumerate before they complete)
                    from veles_tpu.serving import openai_api
                    self._reply_json(openai_api.models_reply())
                    return
                if route == "/alerts":
                    # the replica-tier alert engine: firing/pending
                    # instances + the loaded rule set
                    if api.alerts_ is None:
                        self._reply_json({"enabled": False})
                        return
                    self._reply_json(api.alerts_.snapshot())
                    return
                if route == "/metrics/history":
                    # windowed queries over the replica's embedded
                    # history store (?series=...&window=...&agg=...
                    # &label.<k>=<v>&tier=N; no series = catalog)
                    if api.tsdb_ is None:
                        self._reply_json({"enabled": False},
                                         code=503)
                        return
                    from veles_tpu.telemetry.tsdb import \
                        history_query
                    query = self.path.partition("?")[2]
                    self._reply_json(
                        history_query(api.tsdb_, query))
                    return
                if route == "/metrics":
                    # Prometheus text exposition of the process-wide
                    # registry (serving, per-unit, compile series)
                    from veles_tpu.telemetry import metrics as registry
                    blob = registry.render_prometheus().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8")
                    self.send_header("Content-Length", str(len(blob)))
                    self.end_headers()
                    self.wfile.write(blob)
                    return
                self.send_error(404)

            def _reply_json(self, obj, code=200):
                blob = json.dumps(obj, default=str).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                if api.replica_id:
                    self.send_header("X-Veles-Replica",
                                     str(api.replica_id))
                self.send_header(reqtrace.TRACE_HEADER,
                                 self._trace())
                self.send_header("Content-Length", str(len(blob)))
                self.end_headers()
                self.wfile.write(blob)

            def _reply_binary(self, blob, code=200):
                """Raw-bytes reply for the zero-copy KV wire
                (``application/x-veles-kv``): no JSON, no base64 —
                the body IS the frame."""
                from veles_tpu.serving.disagg import \
                    WIRE_CONTENT_TYPE
                self.send_response(code)
                self.send_header("Content-Type", WIRE_CONTENT_TYPE)
                if api.replica_id:
                    self.send_header("X-Veles-Replica",
                                     str(api.replica_id))
                self.send_header(reqtrace.TRACE_HEADER,
                                 self._trace())
                self.send_header("Content-Length", str(len(blob)))
                self.end_headers()
                self.wfile.write(blob)

            def _wants_binary(self):
                from veles_tpu.serving.disagg import \
                    WIRE_CONTENT_TYPE
                return WIRE_CONTENT_TYPE in \
                    (self.headers.get("Accept") or "")

            def _sent_binary(self):
                from veles_tpu.serving.disagg import \
                    WIRE_CONTENT_TYPE
                ctype = (self.headers.get("Content-Type")
                         or "").split(";")[0].strip().lower()
                return ctype == WIRE_CONTENT_TYPE

            def _read_raw(self):
                length = int(self.headers.get("Content-Length", 0))
                return self.rfile.read(length)

            def _reply_error(self, code, message, retry_after=None,
                             **extra):
                """Structured error reply: ``{"error": {"code",
                "message", "trace_id", ...}}``; a 503's Retry-After
                header tells retrying clients (and the router) when
                this replica is worth another attempt, and the trace
                id makes the FAILURE correlatable with the server-
                side phase timeline — not just successes."""
                err = {"code": int(code),
                       "message": str(message or ""),
                       "trace_id": self._trace()}
                err.update({k: v for k, v in extra.items()
                            if v is not None})
                blob = json.dumps({"error": err},
                                  default=str).encode()
                self.send_response(int(code))
                self.send_header("Content-Type", "application/json")
                if api.replica_id:
                    self.send_header("X-Veles-Replica",
                                     str(api.replica_id))
                self.send_header(reqtrace.TRACE_HEADER,
                                 self._trace())
                if retry_after is not None:
                    self.send_header("Retry-After",
                                     str(max(1, int(retry_after))))
                self.send_header("Content-Length", str(len(blob)))
                self.end_headers()
                if getattr(self, "command", None) != "HEAD":
                    self.wfile.write(blob)

            def send_error(self, code, message=None, explain=None):
                # every error path (including the base class's own
                # calls) answers the structured JSON body — ad-hoc
                # HTML error pages are not machine-parseable
                self._reply_error(code, message or explain or "")

            def _read_body(self):
                length = int(self.headers.get("Content-Length", 0))
                return json.loads(self.rfile.read(length) or b"{}")

            def _reply_scheduler_error(self, e):
                """Map a SchedulerError to its structured HTTP reply
                (503 + class-aware Retry-After, 408 + partial-token
                count) — shared by /generate and the /v1 facade."""
                self._reply_error(
                    e.http_status, _status_text(e),
                    retry_after=getattr(e, "retry_after", None),
                    tokens_generated=getattr(e, "tokens_generated",
                                             None),
                    draining=True if api._draining_ else None)

            def _sse_headers(self):
                """Begin a Server-Sent-Events response; the
                connection close delimits the stream (HTTP/1.0 —
                no Content-Length)."""
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                if api.replica_id:
                    self.send_header("X-Veles-Replica",
                                     str(api.replica_id))
                self.send_header(reqtrace.TRACE_HEADER,
                                 self._trace())
                self.end_headers()
                self.close_connection = True

            def _relay_sse(self, ts, chunk_fn, final_fn):
                """Pump one TokenStream onto the wire: one SSE frame
                per accepted token (``chunk_fn(token) -> payload``),
                ``final_fn(error_or_None) -> payload`` as the
                terminal frame, then ``data: [DONE]``.  A client that
                disconnects mid-stream CANCELS the request — its slot
                and KV blocks return to the pool at the next decode
                boundary instead of decoding for nobody."""
                import time as _time

                from veles_tpu.serving.scheduler import SchedulerError
                from veles_tpu.serving.streams import (
                    SSE_DONE, StreamTimeoutError, sse_event)
                # backstop against a wedged loop with the watchdog
                # off: stop waiting, cancel, tell the client
                ts.token_timeout = api.request_timeout + 30.0
                tron = api.scheduler_ is not None \
                    and api.scheduler_._tron
                t0 = _time.monotonic()
                self._sse_headers()
                err = None
                try:
                    for tok in ts:
                        self.wfile.write(sse_event(chunk_fn(tok)))
                        self.wfile.flush()
                except (BrokenPipeError, ConnectionError, OSError):
                    ts.cancel()
                    if tron:
                        reqtrace.record(
                            ts.trace, "stream",
                            duration=_time.monotonic() - t0,
                            tokens=len(ts.tokens),
                            outcome="disconnect")
                    return
                except StreamTimeoutError as e:
                    ts.cancel()
                    err = SchedulerError(_status_text(e))
                except SchedulerError as e:
                    err = e
                try:
                    self.wfile.write(sse_event(final_fn(err)))
                    self.wfile.write(SSE_DONE)
                    self.wfile.flush()
                except (BrokenPipeError, ConnectionError, OSError):
                    pass
                if tron:
                    # the delivery span: how long the wire emission
                    # ran and how many tokens it carried
                    reqtrace.record(
                        ts.trace, "stream",
                        duration=_time.monotonic() - t0,
                        tokens=len(ts.tokens),
                        outcome="ok" if err is None
                        else type(err).__name__)

            def _stream_generate(self, row, steps, temperature,
                                 top_k, seed, stop, priority,
                                 resume=None):
                """SSE for POST /generate {"stream": true}: one
                ``{"token": t}`` frame per accepted token (spec
                bursts arrive back to back), a terminal frame with
                the FULL token list (concatenation check: identical
                to the batch reply) + usage, then [DONE].  With
                ``resume`` (the failover lane) only the NEWLY drawn
                tokens stream — the terminal frame still carries the
                complete prompt + resumed + new list, so a router
                splicing the continuation into an interrupted stream
                delivers a terminal frame byte-identical to the
                uninterrupted run's."""
                from veles_tpu.serving.scheduler import SchedulerError
                resume = resume or []
                try:
                    ts = api.scheduler_.submit(
                        row, steps, temperature=temperature,
                        top_k=top_k,
                        seed=None if seed is None else int(seed),
                        stop_token=stop,
                        timeout=api.request_timeout,
                        priority=priority, stream=True,
                        trace=self._trace(),
                        resume_tokens=resume,
                        tenant=self._tenant())
                except ValueError as e:
                    self.send_error(400, _status_text(e))
                    return
                except SchedulerError as e:
                    self._reply_scheduler_error(e)
                    return

                def final(err):
                    # terminal/usage frames carry the trace id so a
                    # streamed reply (success OR failure) correlates
                    # with the server-side phase timeline
                    if err is not None:
                        return {"error": {
                            "code": getattr(err, "http_status", 500),
                            "message": _status_text(err),
                            "trace_id": ts.trace,
                            "tokens_generated": len(ts.tokens)}}
                    done = resume + ts.tokens
                    return {"done": True,
                            "tokens": ts.prompt + done,
                            "trace_id": ts.trace,
                            "usage": {
                                "prompt_tokens": len(ts.prompt),
                                "completion_tokens": len(done),
                                "total_tokens": len(ts.prompt)
                                + len(done)}}

                self._relay_sse(ts, lambda t: {"token": t}, final)

            def _v1_completions(self):
                """POST /v1/completions — the OpenAI facade over the
                same scheduler path /generate uses (stream and
                batch)."""
                from veles_tpu.serving import openai_api
                from veles_tpu.serving.scheduler import SchedulerError
                if api.forwards is None:
                    self.send_error(404,
                                    "this endpoint serves no model")
                    return
                try:
                    params = openai_api.parse_completions(
                        self._read_body())
                except ValueError as e:
                    self.send_error(400, _status_text(e))
                    return
                rows = params["rows"]
                if len(rows) > api._cap("max_batch", 64):
                    self.send_error(400, "batch of %d prompts "
                                    "exceeds max_batch" % len(rows))
                    return
                if params["steps"] > api._cap("max_steps", 2048):
                    self.send_error(400, "max_tokens %d exceeds "
                                    "max_steps" % params["steps"])
                    return
                err = api._validate_rows(rows)
                if err:
                    self.send_error(400, err)
                    return
                if api.scheduler_ is None:
                    self.send_error(
                        501, "the OpenAI facade needs the serving "
                        "scheduler (serving=False pins legacy "
                        "/generate only)")
                    return
                import time as _time
                cid = openai_api.completion_id()
                created = int(_time.time())
                model = params["model"]
                if params["stream"]:
                    if len(rows) != 1:
                        self.send_error(400, "stream: true needs a "
                                        "single prompt row")
                        return
                    try:
                        ts = api.scheduler_.submit(
                            rows[0], params["steps"],
                            temperature=params["temperature"],
                            top_k=params["top_k"],
                            seed=params["seed"],
                            stop_token=params["stop"],
                            timeout=api.request_timeout,
                            priority=params["priority"],
                            stream=True, trace=self._trace(),
                            tenant=self._tenant())
                    except ValueError as e:
                        self.send_error(400, _status_text(e))
                        return
                    except SchedulerError as e:
                        self._reply_scheduler_error(e)
                        return

                    def chunk(tok):
                        return openai_api.completion_chunk(
                            cid, created, model, 0, [tok])

                    def final(err):
                        if err is not None:
                            return {"error": {
                                "code": getattr(err, "http_status",
                                                500),
                                "message": _status_text(err),
                                "trace_id": ts.trace}}
                        return openai_api.completion_chunk(
                            cid, created, model, 0, [],
                            finish=openai_api.finish_reason(
                                ts.tokens, params["steps"],
                                params["stop"]),
                            usage=openai_api.usage_of(
                                rows, [len(ts.tokens)]),
                            trace_id=ts.trace)

                    self._relay_sse(ts, chunk, final)
                    return
                try:
                    outs = api._generate_scheduled(
                        rows, params["steps"], params["temperature"],
                        params["top_k"], params["seed"],
                        params["stop"], priority=params["priority"],
                        trace=self._trace(),
                        tenant=self._tenant())
                except ValueError as e:
                    self.send_error(400, _status_text(e))
                    return
                except SchedulerError as e:
                    self._reply_scheduler_error(e)
                    return
                except concurrent.futures.TimeoutError:
                    self._reply_error(408, "decode timed out",
                                      tokens_generated=0)
                    return
                gens = [out[len(r):] for r, out in zip(rows, outs)]
                choices = [openai_api.completion_choice(i, r, g,
                                                        params)
                           for i, (r, g) in enumerate(zip(rows,
                                                          gens))]
                self._reply_json(openai_api.completion_reply(
                    cid, created, model, choices,
                    openai_api.usage_of(rows,
                                        [len(g) for g in gens])))

            def _v1_batch(self, kind):
                """POST /v1/embeddings | /v1/classify — batched
                non-LM scoring through the scheduler's aux lane (the
                decode loop runs the jitted pass between decode
                boundaries)."""
                from veles_tpu.serving import openai_api
                from veles_tpu.serving.scheduler import SchedulerError
                if api.forwards is None or api.scheduler_ is None:
                    self.send_error(404, "no servable model chain")
                    return
                try:
                    body = self._read_body()
                    rows, _ = openai_api.parse_token_rows(
                        body.get("input"), what="input")
                except ValueError as e:
                    self.send_error(400, _status_text(e))
                    return
                if len(rows) > api._cap("max_batch", 64):
                    self.send_error(400, "batch of %d rows exceeds "
                                    "max_batch" % len(rows))
                    return
                err = api._validate_rows(rows)
                if err:
                    self.send_error(400, err)
                    return
                model = str(body.get("model")
                            or openai_api.model_id())
                try:
                    if kind == "embed":
                        fut = api.scheduler_.submit_embed(rows)
                    else:
                        fut = api.scheduler_.submit_score(rows)
                    out = fut.result(api.request_timeout + 30.0)
                except ValueError as e:
                    self.send_error(400, _status_text(e))
                    return
                except SchedulerError as e:
                    self._reply_scheduler_error(e)
                    return
                except concurrent.futures.TimeoutError:
                    self._reply_error(408, "scoring timed out")
                    return
                if kind == "embed":
                    self._reply_json(openai_api.embeddings_reply(
                        model, out, rows))
                else:
                    try:
                        top = int(body.get("top", 5))
                    except (TypeError, ValueError):
                        self.send_error(400, "top must be an int")
                        return
                    self._reply_json(openai_api.classify_reply(
                        model, out, rows, top))

            def _serving_prefill(self):
                """POST /serving/prefill — the disaggregated fleet's
                prefill half (roles "prefill"/"both"): chunk-prefill
                one prompt row, park its raw KV blocks + first-token
                logits under a handle, reply the handle.  The decode
                half fetches the export and POSTs it to
                /serving/kv_import on a decode replica."""
                from veles_tpu.serving.scheduler import SchedulerError
                if api.forwards is None or api.scheduler_ is None:
                    self.send_error(404, "no servable model chain")
                    return
                try:
                    body = self._read_body()
                    prompt = body.get("prompt")
                    if not isinstance(prompt, list) or not prompt \
                            or isinstance(prompt[0], list):
                        self.send_error(
                            400, "prompt must be ONE flat token "
                            "list (prefill export is per-request)")
                        return
                    rows = [[int(t) for t in prompt]]
                except (TypeError, ValueError):
                    self.send_error(400, "prompt must be a flat "
                                    "list of token ids")
                    return
                err = api._validate_rows(rows)
                if err:
                    self.send_error(400, err)
                    return
                try:
                    fut = api.scheduler_.submit_prefill(
                        rows[0], seed=body.get("seed"),
                        timeout=api.request_timeout,
                        priority=body.get("priority"),
                        trace=self._trace())
                    out = fut.result(api.request_timeout + 30.0)
                except ValueError as e:
                    self.send_error(400, _status_text(e))
                    return
                except SchedulerError as e:
                    self._reply_scheduler_error(e)
                    return
                except concurrent.futures.TimeoutError:
                    self._reply_error(408, "prefill timed out")
                    return
                out["trace_id"] = self._trace()
                self._reply_json(out)

            def _serving_kv_import(self):
                """POST /serving/kv_import — the decode half (roles
                "decode"/"both"): adopt an exported prefill record
                and decode; replies like a single-row /generate."""
                from veles_tpu.serving.disagg import (
                    decode_export, decode_export_binary)
                from veles_tpu.serving.scheduler import SchedulerError
                if api.forwards is None or api.scheduler_ is None:
                    self.send_error(404, "no servable model chain")
                    return
                try:
                    if self._sent_binary():
                        # binary frame: the record is the body, the
                        # sampler parameters ride the frame header's
                        # "extra" dict
                        export, body = decode_export_binary(
                            self._read_raw())
                    else:
                        body = self._read_body()
                        export = decode_export(
                            body.get("export") or {})
                    steps = int(body.get("steps", 0))
                    temperature = float(body.get("temperature")
                                        or 0.0)
                    top_k = int(body.get("top_k") or 0)
                    stop = body.get("stop")
                    stop = int(stop) if stop is not None else None
                except (TypeError, ValueError) as e:
                    self.send_error(400, _status_text(e))
                    return
                if steps > api._cap("max_steps", 2048):
                    self.send_error(400, "steps %d exceeds "
                                    "max_steps" % steps)
                    return
                try:
                    fut = api.scheduler_.submit_imported(
                        export, steps, temperature=temperature,
                        top_k=top_k, seed=body.get("seed"),
                        stop_token=stop,
                        timeout=api.request_timeout,
                        priority=body.get("priority"),
                        trace=self._trace())
                    toks = fut.result(api.request_timeout + 30.0)
                except ValueError as e:
                    self.send_error(400, _status_text(e))
                    return
                except SchedulerError as e:
                    self._reply_scheduler_error(e)
                    return
                except concurrent.futures.TimeoutError:
                    self._reply_error(408, "decode timed out",
                                      tokens_generated=0)
                    return
                self._reply_json({"tokens": toks})

            def _serving_prefix_export(self):
                """POST /serving/prefix_export — the fleet-wide
                prefix store's read half: body ``{"tokens": [...]}``,
                reply the raw KV blocks of the longest resident
                prefix of those tokens across both tiers (binary
                frame when Accept negotiates it), or 404 when
                nothing is resident.  Unlike /generate this WORKS on
                a draining replica — rescuing a drained peer's warm
                cache is the point."""
                from veles_tpu.serving.disagg import (
                    encode_export, encode_export_binary)
                from veles_tpu.serving.scheduler import SchedulerError
                if api.forwards is None or api.scheduler_ is None:
                    self.send_error(404, "no servable model chain")
                    return
                try:
                    body = self._read_body()
                    tokens = [int(t) for t in body.get("tokens")
                              or ()]
                except (TypeError, ValueError):
                    self.send_error(400, "tokens must be a flat "
                                    "list of token ids")
                    return
                try:
                    fut = api.scheduler_.submit_prefix_export(tokens)
                    rec = fut.result(api.request_timeout + 30.0)
                except ValueError as e:
                    self.send_error(400, _status_text(e))
                    return
                except SchedulerError as e:
                    self._reply_scheduler_error(e)
                    return
                except concurrent.futures.TimeoutError:
                    self._reply_error(408, "prefix export timed out")
                    return
                if rec is None:
                    self._reply_error(404, "no resident prefix for "
                                      "these tokens")
                    return
                if self._wants_binary():
                    self._reply_binary(encode_export_binary(rec))
                    return
                self._reply_json(encode_export(rec))

            def _serving_prefix_import(self):
                """POST /serving/prefix_import — the write half: the
                router ships a peer's prefix_export record here
                (binary frame, or legacy JSON under ``{"record":
                ...}``); new chunks join this replica's radix cache
                so the request behind the transfer — and every later
                one — admits warm.  Replies ``{"blocks": adopted}``."""
                from veles_tpu.serving.disagg import (
                    decode_export, decode_export_binary)
                from veles_tpu.serving.scheduler import SchedulerError
                if api.forwards is None or api.scheduler_ is None:
                    self.send_error(404, "no servable model chain")
                    return
                try:
                    if self._sent_binary():
                        record, _ = decode_export_binary(
                            self._read_raw())
                    else:
                        record = decode_export(
                            self._read_body().get("record") or {})
                except (TypeError, ValueError) as e:
                    self.send_error(400, _status_text(e))
                    return
                try:
                    fut = api.scheduler_.submit_prefix_import(record)
                    out = fut.result(api.request_timeout + 30.0)
                except ValueError as e:
                    self.send_error(400, _status_text(e))
                    return
                except SchedulerError as e:
                    self._reply_scheduler_error(e)
                    return
                except concurrent.futures.TimeoutError:
                    self._reply_error(408, "prefix import timed out")
                    return
                self._reply_json(out)

            def do_POST(self):
                self._trace_ = None  # fresh id per request
                self._tenant_ = None
                route = self.path.split("?")[0].rstrip("/")
                if route in ("/serving/prefill",
                             "/serving/kv_import"):
                    try:
                        faults.fire("restful.generate")
                        if route == "/serving/prefill":
                            self._serving_prefill()
                        else:
                            self._serving_kv_import()
                    except faults.InjectedHTTPError as e:
                        self._reply_error(
                            e.status, _status_text(e),
                            retry_after=1 if e.status == 503
                            else None)
                    except Exception as e:
                        self.send_error(500, _status_text(e))
                    return
                if route in ("/serving/prefix_export",
                             "/serving/prefix_import"):
                    # deliberately NOT behind restful.generate: a
                    # prefix transfer is cache plumbing, not a
                    # client request — its faults are injected at
                    # the router's router.prefix.fetch point
                    try:
                        if route == "/serving/prefix_export":
                            self._serving_prefix_export()
                        else:
                            self._serving_prefix_import()
                    except Exception as e:
                        self.send_error(500, _status_text(e))
                    return
                if route == "/v1/completions":
                    try:
                        faults.fire("restful.generate")
                        self._v1_completions()
                    except faults.InjectedHTTPError as e:
                        self._reply_error(
                            e.status, _status_text(e),
                            retry_after=1 if e.status == 503
                            else None)
                    except Exception as e:
                        self.send_error(500, _status_text(e))
                    return
                if route in ("/v1/embeddings", "/v1/classify"):
                    try:
                        faults.fire("restful.generate")
                        self._v1_batch("embed"
                                       if route == "/v1/embeddings"
                                       else "score")
                    except faults.InjectedHTTPError as e:
                        self._reply_error(
                            e.status, _status_text(e),
                            retry_after=1 if e.status == 503
                            else None)
                    except Exception as e:
                        self.send_error(500, _status_text(e))
                    return
                if self.path.rstrip("/") == "/serving/tune":
                    # the control plane's knob surface: the
                    # FleetController nudges shed_block_factor here
                    # under KV pressure.  Guarded like /drain — an
                    # open tuner is a shed-policy bypass — and the
                    # factor floors at 0.1 so no tune can disable
                    # admission shedding outright.
                    if not self._admin_ok():
                        self.send_error(
                            403, "tune needs loopback or the admin "
                            "token")
                        return
                    if api.scheduler_ is None:
                        self.send_error(
                            501, "tune needs the serving scheduler")
                        return
                    try:
                        body = self._read_body()
                        factor = body.get("shed_block_factor")
                        if factor is not None:
                            api.scheduler_.shed_block_factor = \
                                max(0.1, float(factor))
                    except (TypeError, ValueError) as e:
                        self.send_error(400, _status_text(e))
                        return
                    self._reply_json({
                        "shed_block_factor":
                            api.scheduler_.shed_block_factor,
                        "kv_blocks": api.scheduler_.kv_blocks})
                    return
                if self.path.rstrip("/") == "/shutdown":
                    # control-plane guard: when serving beyond loopback,
                    # only loopback peers (or a bearer of the admin
                    # token) may stop the workflow — an open /shutdown
                    # is a one-request denial of service
                    if not self._admin_ok():
                        self.send_error(
                            403, "shutdown needs loopback or the "
                            "admin token")
                        return
                    self._reply_json({"ok": True})
                    if api.shutdown_callback is not None:
                        api.shutdown_callback()
                    return
                if self.path.rstrip("/") == "/drain":
                    # rolling-restart hook: stop admitting (new
                    # submits 503 + Retry-After), finish in-flight,
                    # flip /healthz to 503 so the router drains this
                    # replica.  Guarded like /shutdown (an open drain
                    # is a one-request traffic blackhole), but the
                    # admin token lets a REMOTE router drain replicas
                    # it cannot reach over loopback.
                    if not self._admin_ok():
                        self.send_error(
                            403, "drain needs loopback or the admin "
                            "token")
                        return
                    api._draining_ = True
                    reply = {"draining": True}
                    if api.scheduler_ is not None:
                        api.scheduler_.drain()
                        reply["in_flight"] = api.scheduler_.in_flight
                        reply["drained"] = api.scheduler_.drained
                    self._reply_json(reply, code=202)
                    return
                if self.path.rstrip("/") == "/generate":
                    if api.forwards is None:
                        self.send_error(
                            404, "this endpoint serves no LM chain")
                        return
                    try:
                        faults.fire("restful.generate")
                        length = int(
                            self.headers.get("Content-Length", 0))
                        body = json.loads(self.rfile.read(length))
                        raw = body.get("prompt")
                        if not isinstance(raw, list):
                            # a scalar / missing / object prompt is a
                            # CLIENT error, not a 500 (ADVICE r5)
                            self.send_error(
                                400, "prompt must be a token list or "
                                "a batch of token lists")
                            return
                        squeeze = bool(raw) and \
                            not isinstance(raw[0], list)
                        rows = [raw] if squeeze else list(raw)
                        max_batch = api._cap("max_batch", 64)
                        if len(rows) > max_batch:
                            self.send_error(
                                400, "batch of %d prompts exceeds "
                                "max_batch %d" % (len(rows),
                                                  max_batch))
                            return
                        try:
                            lens = [len(r) for r in rows]
                        except TypeError:
                            self.send_error(
                                400, "prompt rows must be flat "
                                "lists of token ids")
                            return
                        if not rows or min(lens, default=0) < 1:
                            self.send_error(
                                400, "prompt rows must be non-empty "
                                "token lists")
                            return
                        # rows may be RAGGED: pad to the widest and
                        # hand the true lengths to the decode
                        width = max(lens)
                        prompt = numpy.zeros((len(rows), width),
                                             numpy.int32)
                        for i, r in enumerate(rows):
                            try:
                                row = numpy.asarray(r, numpy.int32)
                                if row.ndim != 1:
                                    raise ValueError(row.ndim)
                            except (TypeError, ValueError):
                                # nested/mixed rows are CLIENT errors,
                                # not server faults
                                self.send_error(
                                    400, "prompt rows must be flat "
                                    "lists of token ids")
                                return
                            prompt[i, :len(r)] = row
                        err = api._validate_prompt(prompt)
                        if err:
                            self.send_error(400, err)
                            return
                        try:
                            steps = int(body["steps"])
                            if steps < 0:
                                raise ValueError(steps)
                        except (KeyError, TypeError, ValueError):
                            # client error, not a server fault
                            # (ADVICE r5 #1)
                            self.send_error(
                                400, "steps must be a non-negative "
                                "int")
                            return
                        max_steps = api._cap("max_steps", 2048)
                        if steps > max_steps:
                            # an unbounded steps request costs a
                            # giant decode-window alloc + a fresh
                            # multi-second compile — cap it
                            self.send_error(
                                400, "steps %d exceeds max_steps %d"
                                % (steps, max_steps))
                            return
                        try:
                            temperature = float(
                                body.get("temperature", 0.0))
                            top_k = int(body.get("top_k", 0))
                        except (TypeError, ValueError):
                            self.send_error(
                                400, "temperature must be a number "
                                "and top_k an int")
                            return
                        stop = body.get("stop")
                        if stop is not None:
                            try:
                                stop = int(stop)
                            except (TypeError, ValueError):
                                self.send_error(
                                    400, "stop must be an int "
                                    "token id")
                                return
                        ragged = min(lens) != width
                        try:
                            beam = int(body.get("beam", 0))
                        except (TypeError, ValueError):
                            self.send_error(400, "beam must be an int")
                            return
                        if beam < 0:
                            self.send_error(400, "beam must be >= 1")
                            return
                        priority = body.get("priority")
                        if priority is not None:
                            from veles_tpu.serving.scheduler import \
                                resolve_priority
                            try:
                                resolve_priority(priority)
                            except ValueError as e:
                                self.send_error(400, _status_text(e))
                                return
                        resume = body.get("resume_tokens")
                        if resume is not None:
                            # the mid-stream-failover resume lane: a
                            # router re-submits an interrupted
                            # request with the tokens it already
                            # forwarded; the scheduler re-prefills
                            # prompt + prefix and continues at draw
                            # counter len(resume).  Loopback/admin
                            # only — an open resume lane would let
                            # any client bill continuations against
                            # arbitrary fabricated prefixes
                            if not self._admin_ok():
                                self.send_error(
                                    403, "resume_tokens is the "
                                    "loopback/admin failover lane")
                                return
                            try:
                                resume = [int(t) for t in resume]
                            except (TypeError, ValueError):
                                self.send_error(
                                    400, "resume_tokens must be a "
                                    "flat list of token ids")
                                return
                            rerr = api._validate_rows([resume]) \
                                if resume else None
                            if rerr:
                                self.send_error(400, rerr)
                                return
                            if beam or len(rows) != 1 \
                                    or api.scheduler_ is None \
                                    or steps < 1:
                                self.send_error(
                                    400, "resume_tokens needs the "
                                    "serving scheduler, a single "
                                    "prompt row, steps >= 1 and no "
                                    "beam")
                                return
                        if body.get("stream"):
                            # SSE token streaming rides the serving
                            # scheduler only (the legacy lockstep
                            # decode has no incremental tokens)
                            if beam:
                                self.send_error(
                                    400, "stream does not combine "
                                    "with beam search")
                                return
                            if api.scheduler_ is None or steps < 1:
                                self.send_error(
                                    400, "stream: true needs the "
                                    "serving scheduler and steps "
                                    ">= 1")
                                return
                            if len(rows) != 1:
                                self.send_error(
                                    400, "stream: true needs a "
                                    "single prompt row")
                                return
                            self._stream_generate(
                                rows[0], steps, temperature, top_k,
                                body.get("seed"), stop, priority,
                                resume=resume)
                            return
                        if beam:
                            if temperature or top_k:
                                self.send_error(
                                    400, "beam search is deterministic"
                                    " - drop temperature/top_k")
                                return
                            if stop is not None:
                                self.send_error(
                                    400, "beam search decodes fixed "
                                    "length - drop stop")
                                return
                            if ragged:
                                self.send_error(
                                    400, "beam search needs equal-"
                                    "length prompts")
                                return
                            try:
                                toks, scores = api._decode_beam(
                                    prompt, steps, beam)
                            except ValueError as e:
                                # beam > vocab / non-cacheable chain:
                                # the client's request, not our fault
                                self.send_error(400, _status_text(e))
                                return
                            toks = numpy.asarray(toks).tolist()
                            scores = numpy.asarray(scores).tolist()
                            reply = {"tokens": [r[0] for r in toks],
                                     "beams": toks, "scores": scores}
                            if squeeze:
                                reply = {"tokens": toks[0][0],
                                         "beams": toks[0],
                                         "scores": scores[0]}
                            self._reply_json(reply)
                            return
                        if api.scheduler_ is not None and steps >= 1:
                            # continuous batching: rows join decode
                            # slots independently — NO lock, so
                            # concurrent clients interleave
                            from veles_tpu.serving.scheduler import \
                                SchedulerError
                            try:
                                outs = api._generate_scheduled(
                                    rows, steps, temperature, top_k,
                                    body.get("seed"), stop,
                                    priority=priority,
                                    trace=self._trace(),
                                    resume_tokens=resume,
                                    tenant=self._tenant())
                            except ValueError as e:
                                self.send_error(400, _status_text(e))
                                return
                            except SchedulerError as e:
                                # 503s carry Retry-After; a deadline
                                # 408 reports the partial decode the
                                # client paid for before expiry
                                self._reply_error(
                                    e.http_status, _status_text(e),
                                    retry_after=getattr(
                                        e, "retry_after", None),
                                    tokens_generated=getattr(
                                        e, "tokens_generated", None),
                                    draining=True
                                    if api._draining_ else None)
                                return
                            except concurrent.futures.TimeoutError:
                                self._reply_error(
                                    408, "decode timed out",
                                    tokens_generated=0)
                                return
                            self._reply_json(
                                {"tokens": outs[0] if squeeze
                                 else outs})
                            return
                        tokens = api._decode(
                            prompt, steps, temperature, top_k,
                            body.get("seed"),
                            prompt_lens=lens if ragged else None,
                            stop_token=stop)
                        tokens = numpy.asarray(tokens)
                        # each row answers with ITS prompt + steps
                        # tokens (shorter rows decode past their quota
                        # in lockstep; the surplus is sliced off), cut
                        # at the first GENERATED stop token if one was
                        # requested (the stop itself stays in)
                        out = []
                        for i in range(len(rows)):
                            row = tokens[i, :lens[i] + steps]
                            if stop is not None:
                                hits = numpy.nonzero(
                                    row[lens[i]:] == int(stop))[0]
                                if hits.size:
                                    row = row[:lens[i] + hits[0] + 1]
                            out.append(row.tolist())
                        self._reply_json(
                            {"tokens": out[0] if squeeze else out})
                    except faults.InjectedHTTPError as e:
                        # the http_error fault action: REPLY the
                        # injected status as a structured error (a
                        # deliberately-failing replica, not a crash)
                        self._reply_error(
                            e.status, _status_text(e),
                            retry_after=1 if e.status == 503
                            else None)
                    except Exception as e:
                        self.send_error(500, _status_text(e))
                    return
                if self.path.rstrip("/") != "/api":
                    self.send_error(404)
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(length))
                    sample = numpy.asarray(body["input"], numpy.float32)
                    future = api.loader.feed_request(sample)
                    result = future.result(api.request_timeout)
                    self._reply_json({"result": result})
                except Exception as e:  # one bad request must not kill
                    self.send_error(500, _status_text(e))  # the server

        self._server_ = ThreadingHTTPServer((self.host, self.port),
                                            Handler)
        self.port = self._server_.server_address[1]
        import os
        self.replica_id = self.replica_id \
            or "pid%d:%d" % (os.getpid(), self.port)
        self._thread_ = threading.Thread(
            target=self._server_.serve_forever, daemon=True,
            name="restful-api")
        self._thread_.start()
        from veles_tpu.config import root as _root
        if self.tsdb_ is None \
                and _root.common.tsdb.get("enabled", True):
            from veles_tpu.telemetry.tsdb import TimeSeriesStore
            self.tsdb_ = TimeSeriesStore(
                name=self.replica_id or "replica").start()
        if self.alerts_ is None \
                and _root.common.alerts.get("enabled", True):
            from veles_tpu.telemetry.alerts import AlertEngine
            self.alerts_ = AlertEngine(
                name=self.replica_id or "replica",
                tsdb=self.tsdb_).start()
        self.info("REST API on http://%s:%d/api", self.host, self.port)

    def run(self):
        futures = getattr(self.loader, "pending_futures_", [])
        if not futures:
            return
        out = self.output
        if isinstance(out, Array):
            out.map_read()
            out = out.mem
        for i, future in enumerate(futures):
            if not future.done():
                future.set_result(numpy.asarray(out[i]).tolist())
        self.loader.pending_futures_ = []

    def stop(self):
        alerts, self.alerts_ = self.alerts_, None
        if alerts is not None:
            alerts.stop()
        tsdb, self.tsdb_ = self.tsdb_, None
        if tsdb is not None:
            tsdb.stop()
        if self.scheduler_ is not None:
            self.scheduler_.close()
            self.scheduler_ = None
        if self._server_ is not None:
            self._server_.shutdown()
            # close the LISTENING socket too: a stopped replica must
            # refuse new connections (fast router failover) instead
            # of letting them rot in the dead server's accept backlog
            self._server_.server_close()
            self._server_ = None
