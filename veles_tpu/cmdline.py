"""Command line surface (rebuild of veles/cmdline.py:61-278).

The reference aggregated every unit's ``init_parser`` via metaclass; here
units registered in :data:`EXTRA_PARSERS` contribute argument groups to
the single global parser (same capability, explicit registration).
"""

import argparse

#: callables(parser) appended by modules that add CLI flags
EXTRA_PARSERS = []


def add_arguments(fn):
    """Decorator registering an argument contributor."""
    EXTRA_PARSERS.append(fn)
    return fn


def build_parser():
    p = argparse.ArgumentParser(
        prog="veles_tpu",
        description="veles_tpu — TPU-native dataflow deep-learning "
                    "framework: python -m veles_tpu <workflow.py> "
                    "[config.py]")
    p.add_argument("workflow", nargs="?",
                   help="workflow python file (defines run(load, main))")
    p.add_argument("config", nargs="?", default=None,
                   help="config python file (mutates root.*)")
    p.add_argument("-a", "--backend", default=None,
                   help="device backend: tpu|gpu|numpy|auto "
                        "(ref: veles -a flag)")
    p.add_argument("-d", "--device", type=int, default=0,
                   help="device index within the backend")
    p.add_argument("-s", "--snapshot", default=None,
                   help="resume from snapshot file")
    p.add_argument("--decision", action="append", default=[],
                   metavar="KEY=VALUE",
                   help="override a decision-unit attribute after "
                        "(re)construction — e.g. max_epochs=30 or "
                        "fail_iterations=100 to extend a RESUMED "
                        "run, whose pickled stopping state would "
                        "otherwise end it immediately (repeatable)")
    p.add_argument("-c", "--config-override", action="append", default=[],
                   metavar="SNIPPET",
                   help='python snippet, e.g. "root.x.y = 1" '
                        "(repeatable)")
    p.add_argument("--seed", default=None,
                   help="int, or file:N to read N bytes of entropy "
                        "(ref: veles --random-seed)")
    p.add_argument("--result-file", default=None,
                   help="write gathered metrics JSON here")
    p.add_argument("--dump-config", action="store_true",
                   help="print the effective config and exit")
    p.add_argument("--visualize", action="store_true",
                   help="print the workflow graph DOT and exit")
    p.add_argument("-l", "--listen", default=None, metavar="ADDR",
                   help="run as coordinator, listen on host:port")
    p.add_argument("-m", "--master-address", default=None, metavar="ADDR",
                   help="run as worker of the given coordinator")
    p.add_argument("-w", "--workers", default=None, metavar="N|HOSTS",
                   help="with -l: spawn N local worker processes, or a "
                        "comma list of hosts over ssh (ref: veles -n)")
    p.add_argument("-g", "--graphics", action="store_true",
                   help="publish live plot payloads over ZMQ PUB "
                        "(attach: python -m veles_tpu.graphics_client)")
    p.add_argument("--web-status", default=None, metavar="URL",
                   help="POST run status to a veles_tpu.web_status "
                        "dashboard")
    p.add_argument("--optimize", default=None, metavar="SIZE[:GENS]",
                   help="genetic hyper-parameter search over the "
                        "config's Range() tuneables (ref: veles "
                        "--optimize)")
    p.add_argument("--ensemble-train", type=int, default=None,
                   metavar="N", help="train N model instances and "
                   "aggregate results (ref: veles ensemble mode)")
    p.add_argument("--ensemble-test", default=None, metavar="SUMMARY",
                   help="re-run the snapshots of an ensemble summary "
                        "JSON and aggregate metrics")
    p.add_argument("--train-ratio", type=float, default=1.0,
                   help="ensemble: fraction of the train span each "
                        "instance sees")
    p.add_argument("-v", "--verbose", action="count", default=0,
                   help="-v debug, -vv everything")
    p.add_argument("--timings", action="store_true",
                   help="per-unit run timing printout")
    p.add_argument("--frontend", action="store_true",
                   help="serve a browser form to compose the command "
                        "line, then execute the submitted run "
                        "(ref: veles --frontend)")
    p.add_argument("--frontend-port", type=int, default=8070,
                   help="frontend HTTP port")
    p.add_argument("--export-package", default=None, metavar="FILE",
                   help="after the run, export the forward chain as an "
                        "inference package (contents.json + npy + "
                        "StableHLO tar.gz; consumed by load_package and "
                        "runtime/veles_runner)")
    p.add_argument("--debug-pickle", action="store_true",
                   help="after initialize, verify the workflow pickles "
                        "and name any unpicklable attribute paths "
                        "(ref: veles --debug-pickle)")
    p.add_argument("--profile", default=None, metavar="DIR",
                   help="capture a jax.profiler trace of the run into "
                        "DIR (view with tensorboard / xprof); also "
                        "annotates each unit run")
    p.add_argument("--events-log", default=None, metavar="FILE",
                   help="record the span/event stream to a JSONL FILE "
                        "(convert for Perfetto with python -m "
                        "veles_tpu.telemetry.trace_export)")
    p.add_argument("--health-policy", default=None,
                   choices=("warn", "skip_step", "halt"),
                   help="what a NaN/Inf training step triggers: warn "
                        "(log+count), skip_step (drop the update "
                        "in-graph), halt (stop the workflow, keep the "
                        "process up); sets root.common.health.policy")
    p.add_argument("--prefetch", type=int, default=None, nargs="?",
                   const=2, metavar="DEPTH",
                   help="asynchronous input pipeline for streaming "
                        "loaders: decode/upload DEPTH minibatches "
                        "ahead of the training step (bare flag: "
                        "depth 2; 0 pins the synchronous path); sets "
                        "root.common.loader.prefetch")
    p.add_argument("--compilation-cache", default=None, metavar="DIR",
                   help="persistent XLA compilation cache directory "
                        "(jax_compilation_cache_dir) — later runs "
                        "reuse compiled executables instead of paying "
                        "multi-second recompiles; sets "
                        "root.common.trace.compilation_cache_dir")
    p.add_argument("--admin-token", default=None, metavar="TOKEN",
                   help="bearer token a NON-loopback caller must "
                        "present (Authorization: Bearer TOKEN) to hit "
                        "the REST admin endpoints /drain and "
                        "/shutdown — the remote-router rolling-"
                        "restart story; sets "
                        "root.common.api.admin_token (unset: those "
                        "endpoints stay loopback-only)")
    p.add_argument("--flightrec-dir", default=None, metavar="DIR",
                   help="write crash flight-recorder bundles "
                        "(flightrec-<pid>.json) to DIR instead of the "
                        "snapshot dir; the recorder itself installs "
                        "on every CLI run unless "
                        "root.common.flightrec.enabled is False")
    for fn in EXTRA_PARSERS:
        fn(p)
    return p


def filter_argv(argv, *allowed):
    """Keep only known flags — used when re-exec'ing workers
    (ref: veles/launcher.py:75)."""
    out = []
    i = 0
    while i < len(argv):
        a = argv[i]
        key = a.split("=")[0]
        if key in allowed:
            out.append(a)
            if "=" not in a and i + 1 < len(argv) \
                    and not argv[i + 1].startswith("-"):
                out.append(argv[i + 1])
                i += 1
        i += 1
    return out
