"""forge — the model hub (rebuild of veles/forge/): share trained
model packages (the package_export archive format) through a central
server with versioning."""

from veles_tpu.forge.client import fetch, list_packages, upload  # noqa: F401
from veles_tpu.forge.server import ForgeServer, ForgeStore  # noqa: F401
