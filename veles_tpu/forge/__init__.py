"""forge — the model hub (rebuild of veles/forge/): share trained
model packages (the package_export archive format) through a central
server with versioning."""

from veles_tpu.forge.client import (  # noqa: F401
    fetch, list_packages, upload, versions)
from veles_tpu.forge.server import ForgeServer, ForgeStore  # noqa: F401
