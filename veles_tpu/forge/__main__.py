import sys

from veles_tpu.forge.client import main

sys.exit(main())
