"""Forge server — the model hub service (rebuild of
veles/forge/forge_server.py:462).

Stores uploaded model packages (the package_export tar.gz format)
under ``<store>/<name>/<version>/`` with a metadata.json each; serves
list/versions/fetch/upload over HTTP (stdlib threading server — the
reference used Tornado + a git-backed version store,
forge_server.py:103-455).  Version-history semantics: every version is
retained with uploader/timestamp/sha256 metadata, ``/versions?name=``
returns the ordered history, an existing name+version cannot be
silently overwritten (HTTP 409 — the git store's equivalent of
history immutability), and fetches are checksum-verified end to end."""

import hashlib
import json
import os
import re
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from veles_tpu.logger import Logger

_NAME_RE = re.compile(r"^[A-Za-z0-9_.-]{1,64}$")


class VersionExists(ValueError):
    """Re-upload of an existing name+version (history is immutable)."""


class ForgeStore:
    """Filesystem package store with retained version history."""

    def __init__(self, directory):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        # the HTTP front is threaded: the exists-check + blob/metadata
        # writes must be atomic or two racing uploads of one
        # name+version both pass the immutability check and can pair
        # A's blob with B's checksum
        self._write_lock = threading.Lock()

    def _dir(self, name, version):
        if not _NAME_RE.match(name) or not _NAME_RE.match(version):
            raise ValueError("invalid package name/version")
        return os.path.join(self.directory, name, version)

    def save(self, name, version, blob, metadata):
        d = self._dir(name, version)
        with self._write_lock:
            if os.path.isfile(os.path.join(d, "metadata.json")):
                raise VersionExists(
                    "%s==%s already exists — versions are retained "
                    "history, pick a new version" % (name, version))
            os.makedirs(d, exist_ok=True)
            with open(os.path.join(d, "package.tar.gz"), "wb") as f:
                f.write(blob)
            metadata = dict(metadata, name=name, version=version,
                            uploaded=time.time(), size=len(blob),
                            sha256=hashlib.sha256(blob).hexdigest())
            with open(os.path.join(d, "metadata.json"), "w") as f:
                json.dump(metadata, f, indent=1)
        return metadata

    def list(self):
        out = []
        for name in sorted(os.listdir(self.directory)):
            ndir = os.path.join(self.directory, name)
            if not os.path.isdir(ndir):
                continue
            for version in sorted(os.listdir(ndir)):
                meta = os.path.join(ndir, version, "metadata.json")
                if os.path.isfile(meta):
                    with open(meta) as f:
                        out.append(json.load(f))
        return out

    def versions(self, name):
        """Ordered upload history for one package (oldest first)."""
        history = [m for m in self.list() if m["name"] == name]
        if not history:
            raise KeyError(name)
        return sorted(history, key=lambda m: m["uploaded"])

    def fetch(self, name, version=None):
        if version is None:  # latest by upload time
            version = self.versions(name)[-1]["version"]
        d = self._dir(name, version)
        path = os.path.join(d, "package.tar.gz")
        if not os.path.isfile(path):
            raise KeyError("%s==%s" % (name, version))
        with open(path, "rb") as f:
            blob = f.read()
        digest = hashlib.sha256(blob).hexdigest()
        meta_path = os.path.join(d, "metadata.json")
        if os.path.isfile(meta_path):
            with open(meta_path) as f:
                stored = json.load(f).get("sha256")
            if stored and stored != digest:
                raise IOError("stored package %s==%s fails its checksum"
                              % (name, version))
        return blob, version, digest


class ForgeServer(Logger):
    """HTTP front (ref handlers: forge_server.py:103-455)."""

    def __init__(self, directory, port=0, host="127.0.0.1"):
        super(ForgeServer, self).__init__()
        self.store = ForgeStore(directory)
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def _json(self, obj, code=200):
                blob = json.dumps(obj, default=str).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(blob)))
                self.end_headers()
                self.wfile.write(blob)

            def do_GET(self):
                url = urllib.parse.urlparse(self.path)
                q = dict(urllib.parse.parse_qsl(url.query))
                try:
                    if url.path == "/list":
                        self._json(server.store.list())
                    elif url.path == "/versions":
                        self._json(server.store.versions(q["name"]))
                    elif url.path == "/fetch":
                        blob, version, digest = server.store.fetch(
                            q["name"], q.get("version"))
                        self.send_response(200)
                        self.send_header("Content-Type",
                                         "application/gzip")
                        self.send_header("X-Forge-Version", version)
                        self.send_header("X-Forge-Sha256", digest)
                        self.send_header("Content-Length",
                                         str(len(blob)))
                        self.end_headers()
                        self.wfile.write(blob)
                    else:
                        self.send_error(404)
                except KeyError as e:
                    self._json({"error": "not found: %s" % e}, 404)
                except Exception as e:
                    self._json({"error": str(e)[:200]}, 500)

            def do_POST(self):
                url = urllib.parse.urlparse(self.path)
                q = dict(urllib.parse.parse_qsl(url.query))
                if url.path != "/upload":
                    self.send_error(404)
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    blob = self.rfile.read(length)
                    meta = server.store.save(
                        q["name"], q.get("version", "1.0"), blob,
                        {"description": q.get("description", ""),
                         "uploader": q.get("uploader", "")})
                    self._json(meta)
                except VersionExists as e:
                    self._json({"error": str(e)}, 409)
                except Exception as e:
                    self._json({"error": str(e)[:200]}, 400)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]
        self.url = "http://%s:%d" % (host, self.port)
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="forge-server")

    def start(self):
        self._thread.start()
        self.info("forge server on %s (store: %s)", self.url,
                  self.store.directory)
        return self

    def stop(self):
        self._server.shutdown()


def main(argv=None):  # pragma: no cover - service entry
    import argparse
    p = argparse.ArgumentParser(prog="veles_tpu.forge.server")
    p.add_argument("--store", default="forge_store")
    p.add_argument("--port", type=int, default=8190)
    args = p.parse_args(argv)
    server = ForgeServer(args.store, port=args.port)
    server.start()
    threading.Event().wait()


if __name__ == "__main__":  # pragma: no cover
    main()
