"""Forge client (rebuild of veles/forge/forge_client.py:91):
``upload`` / ``fetch`` / ``list`` / version history against a forge
server.  CLI: ``python -m veles_tpu.forge list|fetch|upload ...`` —
the reference exposed the same verbs as ``veles forge <verb>``.
Downloads are verified against the server's sha256."""

import getpass
import hashlib
import json
import os
import urllib.parse
import urllib.request


def list_packages(url, timeout=10):
    with urllib.request.urlopen(url.rstrip("/") + "/list",
                                timeout=timeout) as r:
        return json.load(r)


def versions(url, name, timeout=10):
    """Ordered upload history for one package (oldest first)."""
    full = "%s/versions?%s" % (url.rstrip("/"),
                               urllib.parse.urlencode({"name": name}))
    with urllib.request.urlopen(full, timeout=timeout) as r:
        return json.load(r)


def fetch(url, name, dest, version=None, timeout=30):
    """Download a package (checksum-verified); returns (path, version)."""
    q = {"name": name}
    if version:
        q["version"] = version
    full = "%s/fetch?%s" % (url.rstrip("/"), urllib.parse.urlencode(q))
    with urllib.request.urlopen(full, timeout=timeout) as r:
        got_version = r.headers.get("X-Forge-Version", version or "?")
        expect = r.headers.get("X-Forge-Sha256")
        blob = r.read()
    if expect and hashlib.sha256(blob).hexdigest() != expect:
        raise IOError("fetched %s==%s corrupt: sha256 mismatch"
                      % (name, got_version))
    if os.path.isdir(dest):
        dest = os.path.join(dest, "%s-%s.tar.gz" % (name, got_version))
    with open(dest, "wb") as f:
        f.write(blob)
    return dest, got_version


def upload(url, name, version, package_path, description="",
           uploader=None, timeout=30):
    with open(package_path, "rb") as f:
        blob = f.read()
    if uploader is None:
        try:
            uploader = getpass.getuser()
        except Exception:
            uploader = ""
    q = urllib.parse.urlencode({
        "name": name, "version": version, "description": description,
        "uploader": uploader})
    req = urllib.request.Request(
        "%s/upload?%s" % (url.rstrip("/"), q), data=blob,
        headers={"Content-Type": "application/gzip"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.load(r)


def main(argv=None):
    import argparse
    p = argparse.ArgumentParser(prog="veles_tpu.forge")
    p.add_argument("command", choices=["list", "fetch", "upload"])
    p.add_argument("--server", required=True, help="forge server URL")
    p.add_argument("--name")
    p.add_argument("--version")
    p.add_argument("--versions", action="store_true",
                   help="list: show the full upload history of --name")
    p.add_argument("--package", help="package path (upload)")
    p.add_argument("--dest", default=".", help="output dir (fetch)")
    p.add_argument("--description", default="")
    args = p.parse_args(argv)
    if args.command == "list" and args.versions:
        if not args.name:
            p.error("--versions requires --name")
        for meta in versions(args.server, args.name):
            print("%(name)s %(version)s  %(size)d bytes  "
                  "uploader=%(uploader)s  sha256=%(sha256).12s  "
                  "%(description)s" % dict(
                      {"uploader": "?", "sha256": "?" * 12}, **meta))
    elif args.command == "list":
        for meta in list_packages(args.server):
            print("%(name)s %(version)s  %(size)d bytes  "
                  "%(description)s" % meta)
    elif args.command == "fetch":
        path, version = fetch(args.server, args.name, args.dest,
                              args.version)
        print("fetched %s==%s -> %s" % (args.name, version, path))
    else:
        meta = upload(args.server, args.name, args.version or "1.0",
                      args.package, args.description)
        print("uploaded %(name)s==%(version)s (%(size)d bytes)" % meta)
    return 0


if __name__ == "__main__":  # pragma: no cover
    import sys
    sys.exit(main())
