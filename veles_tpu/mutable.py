"""Shared mutable state primitives for graph control flow.

Rebuild of the reference's veles/mutable.py:

- :class:`Bool` (ref: veles/mutable.py:44-190) — a *shared, mutable*
  boolean cell with lazy expression algebra.  Units hold references to the
  same Bool, so a Decider flipping ``complete`` instantly changes every
  gate built from it (``~complete``, ``complete & other`` …).  Derived
  Bools re-evaluate their expression on every read.
- :class:`LinkableAttribute` (ref: veles/mutable.py:219-357) — property
  forwarding between objects, the mechanism behind ``Unit.link_attrs``:
  reading ``dst.attr`` transparently reads ``src.attr`` (two-way optional).

Both are plain host-side Python — they drive the *scheduler*, never traced
code, so there is no XLA interaction to worry about.
"""


class Bool:
    """Shared mutable boolean with lazy expression algebra.

    ``b = Bool(False)``; ``bool(b)`` reads it; ``b << True`` (or
    ``b.set(True)``) writes it.  ``~a``, ``a & b``, ``a | b``, ``a ^ b``
    build *derived* Bools that re-evaluate lazily, so gates stay live as
    their sources flip (ref: veles/mutable.py:77-85).
    """

    __slots__ = ("_value", "_op", "_sources", "name")

    #: closed op set — named (not lambdas) so expression trees pickle with
    #: structure intact; the reference marshaled lambda code objects instead
    #: (veles/mutable.py:163-190), which is fragile across versions.
    _OPS = {
        "not": lambda a: not a,
        "and": lambda a, b: a and b,
        "or": lambda a, b: a or b,
        "xor": lambda a, b: a != b,
    }

    def __init__(self, value=False, name=None):
        self._value = bool(value)
        self._op = None
        self._sources = ()
        self.name = name

    @classmethod
    def _derived(cls, op, sources, name):
        b = cls(False, name)
        b._op = op
        b._sources = tuple(sources)
        return b

    # -- reading ----------------------------------------------------------

    def __bool__(self):
        if self._op is not None:
            return self._OPS[self._op](*[bool(s) for s in self._sources])
        return self._value

    # -- writing ----------------------------------------------------------

    def set(self, value):
        if self._op is not None:
            raise ValueError("cannot assign to a derived Bool (%s)" % self)
        self._value = bool(value)
        return self

    def __ilshift__(self, value):
        """``b <<= True`` — in-place assignment that keeps identity (other
        holders of this Bool see the change)."""
        return self.set(value)

    def __lshift__(self, value):
        return self.set(value)

    # -- algebra (lazy) ----------------------------------------------------

    def __invert__(self):
        return Bool._derived("not", (self,), "~%s" % self.name)

    def __and__(self, other):
        other = other if isinstance(other, Bool) else Bool(other)
        return Bool._derived("and", (self, other), "&")

    def __or__(self, other):
        other = other if isinstance(other, Bool) else Bool(other)
        return Bool._derived("or", (self, other), "|")

    def __xor__(self, other):
        other = other if isinstance(other, Bool) else Bool(other)
        return Bool._derived("xor", (self, other), "^")

    __rand__ = __and__
    __ror__ = __or__
    __rxor__ = __xor__

    # -- pickling ----------------------------------------------------------
    # Expression structure AND shared identity survive pickling: source
    # Bools are pickled by reference, so within one workflow pickle the
    # memo keeps `cnt.complete` and the gates derived from it wired to the
    # same object after load.

    def __getstate__(self):
        return {"value": self._value, "op": self._op,
                "sources": self._sources, "name": self.name}

    def __setstate__(self, state):
        self._value = state["value"]
        self._op = state["op"]
        self._sources = state["sources"]
        self.name = state.get("name")

    def __reduce__(self):
        return (_rebuild_bool, (self.__getstate__(),))

    def __repr__(self):
        kind = "derived" if self._op is not None else "plain"
        return "<Bool %s %s=%s>" % (kind, self.name or id(self), bool(self))


def _rebuild_bool(state):
    b = Bool()
    b.__setstate__(state)
    return b


def unshadow(cls):
    """The original class beneath any LinkableAttribute shadow class —
    pickling must reference this one, since the shadow is synthetic and
    unimportable."""
    while getattr(cls, "_linkable_shadow_", False) \
            and "_linkable_shadow_" in cls.__dict__:
        cls = cls.__mro__[1]
    return cls


class LinkableAttribute:
    """Forward ``obj.name`` to ``src_obj.src_name``.

    ``LinkableAttribute(dst, "minibatch_data", (loader, "minibatch_data"))``
    installs a property on a per-instance shadow class so only *this* dst
    instance forwards (ref: veles/mutable.py:219-357).  With
    ``two_way=True`` writes propagate back to the source.
    """

    def __init__(self, obj, name, source, two_way=False, assign_now=True):
        src_obj, src_name = source
        self.obj, self.name = obj, name
        self.src_obj, self.src_name = src_obj, src_name
        self.two_way = two_way
        cls = type(obj)
        if not getattr(cls, "_linkable_shadow_", False):
            shadow = type(cls.__name__, (cls,), {"_linkable_shadow_": True})
            obj.__class__ = shadow
        # remove any plain instance attribute that would mask the property
        obj.__dict__.pop(name, None)

        def fget(_self, _src=src_obj, _sn=src_name, _name=name):
            # a one-way write detaches the link: the instance dict then
            # shadows the forwarding property (checked here because a data
            # descriptor otherwise wins over __dict__)
            if _name in _self.__dict__:
                return _self.__dict__[_name]
            return getattr(_src, _sn)

        if two_way:
            def fset(_self, value, _src=src_obj, _sn=src_name):
                setattr(_src, _sn, value)
        else:
            def fset(_self, value, _name=name):
                _self.__dict__[_name] = value

        setattr(type(obj), name, property(fget, fset))
        links = obj.__dict__.setdefault("_linked_attrs_", {})
        links[name] = (src_obj, src_name, two_way)

    @staticmethod
    def unlink(obj, name):
        """Detach a linked attribute, freezing its current value."""
        links = obj.__dict__.get("_linked_attrs_", {})
        if name in links:
            value = getattr(obj, name)
            try:
                delattr(type(obj), name)
            except AttributeError:
                pass
            obj.__dict__[name] = value
            del links[name]
