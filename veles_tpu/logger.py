"""Logging mixin + event tracing.

Rebuild of the reference's Logger (ref: veles/logger.py:59-332): every
framework object mixes in :class:`Logger` and gets ``self.info/debug/...``
bound to a class-named logger, colored console output, and ``event()``
begin/end/single spans — the tracing backbone.

The reference mirrored all records and events to MongoDB
(veles/logger.py:292-332); here the span sink is a JSONL file (cheap,
greppable, no daemon) plus an in-memory ring buffer that the web-status
service reads.  ``jax.profiler`` traces cover the on-device side.
"""

import functools
import json
import logging
import os
import sys
import threading
import time
from collections import deque

_COLORS = {
    logging.DEBUG: "\033[37m",
    logging.INFO: "\033[32m",
    logging.WARNING: "\033[33m",
    logging.ERROR: "\033[31m",
    logging.CRITICAL: "\033[1;31m",
}
_RESET = "\033[0m"


class ColorFormatter(logging.Formatter):
    """Colored console formatter (ref: veles/logger.py:69-114)."""

    def format(self, record):
        msg = super(ColorFormatter, self).format(record)
        if sys.stderr.isatty():
            color = _COLORS.get(record.levelno, "")
            return "%s%s%s" % (color, msg, _RESET)
        return msg


_setup_done = False


def setup_logging(level=logging.INFO, logfile=None):
    """Install the colored root handler once; optional file duplication
    (ref: veles/logger.py:187)."""
    global _setup_done
    if _setup_done:
        logging.getLogger().setLevel(level)
        return
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(ColorFormatter(
        "%(asctime)s %(levelname).1s %(name)s: %(message)s", "%H:%M:%S"))
    logging.getLogger().addHandler(handler)
    if logfile:
        fh = logging.FileHandler(logfile)
        fh.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)s %(name)s: %(message)s"))
        logging.getLogger().addHandler(fh)
    logging.getLogger().setLevel(level)
    _setup_done = True


class EventSink:
    """Process-wide span recorder (ref: Logger.event, veles/logger.py:264-289).

    Spans (`begin`/`end`/`single`) go to a bounded in-memory ring (read by
    the web status dashboard) and, when ``path`` is set, to a JSONL file.
    """

    def __init__(self, maxlen=65536):
        self.ring = deque(maxlen=maxlen)
        self.path = None
        self._lock = threading.Lock()
        self._file = None
        self._warned = False

    def open(self, path):
        # open the NEW file first: if it raises, the previous sink
        # stays intact (and its handle doesn't leak unclosed)
        f = open(path, "a")
        with self._lock:
            if self._file:
                try:
                    self._file.close()
                except OSError:
                    pass
            self._file = f
            self.path = path
            self._warned = False

    def close(self):
        with self._lock:
            if self._file:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None

    def record(self, name, kind, **attrs):
        ev = {"name": name, "kind": kind, "time": time.time(),
              "pid": os.getpid(), "tid": threading.get_ident() & 0xFFFF,
              **attrs}
        with self._lock:
            self.ring.append(ev)
            if self._file:
                try:
                    self._file.write(json.dumps(ev, default=str) + "\n")
                    self._file.flush()
                except (OSError, ValueError):
                    # a failed/closed file must not throw from hot
                    # paths: drop the file sink (ring keeps recording)
                    # with a one-time warning
                    try:
                        self._file.close()
                    except Exception:
                        pass
                    self._file = None
                    if not self._warned:
                        self._warned = True
                        logging.getLogger("EventSink").warning(
                            "span file sink %s failed — file recording "
                            "disabled (in-memory ring still active)",
                            self.path)
        return ev


#: global sink, analogous to the reference's shared Mongo handler.
events = EventSink()


class Logger:
    """Mixin granting named logging + event spans to any class."""

    def __init__(self, **kwargs):
        super(Logger, self).__init__()

    @property
    def logger(self):
        lg = getattr(self, "_logger_", None)
        if lg is None:
            lg = logging.getLogger(type(self).__name__)
            self._logger_ = lg
        return lg

    def debug(self, msg, *args):
        self.logger.debug(msg, *args)

    def info(self, msg, *args):
        self.logger.info(msg, *args)

    def warning(self, msg, *args):
        self.logger.warning(msg, *args)

    def error(self, msg, *args):
        self.logger.error(msg, *args)

    def exception(self, msg="", *args):
        self.logger.exception(msg, *args)

    def event(self, name, kind="single", **attrs):
        """Record a tracing span: kind in {"begin", "end", "single"}
        (ref: veles/logger.py:264-289)."""
        return events.record(name, kind, cls=type(self).__name__, **attrs)

    def timed_event(self, name):
        """Context manager emitting begin/end spans around a block."""
        return _TimedEvent(self, name)


class _TimedEvent:
    def __init__(self, owner, name):
        self.owner, self.name = owner, name

    def __enter__(self):
        self.owner.event(self.name, "begin")
        return self

    def __exit__(self, *exc):
        self.owner.event(self.name, "end")
        return False


def timed(fn):
    """Decorator recording a single span with duration for each call.
    Works on free functions and bound methods alike (the span name is
    the qualified name either way)."""
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        t0 = time.time()
        try:
            return fn(*args, **kwargs)
        finally:
            events.record(fn.__qualname__, "single",
                          duration=time.time() - t0)
    return wrapper
