"""Reproducible seeded randomness (rebuild of veles/prng/).

``get(name)`` returns process-wide named generators exactly like the
reference (ref: veles/prng/random_generator.py:289); every generator
yields both a host-side numpy stream (loader shuffles, CPU init) and
deterministic JAX threefry keys (device-side randomness inside jit),
derived from the same seed.
"""

from veles_tpu.prng.random_generator import (  # noqa: F401
    RandomGenerator, get)
