"""RandomGenerator — one seed, two deterministic streams.

Rebuild of veles/prng/random_generator.py:64-289.  The reference kept
named numpy ``RandomState`` instances whose states were saved/restored
around ``initialize()`` so a resumed run re-initialized bit-identically
(ref: veles/units.py:859-885).  Here each generator derives:

- a **host stream**: ``numpy.random.Generator`` (PCG64) for loader
  shuffling and eager init — full state is pickled with snapshots;
- a **device stream**: JAX threefry keys via a monotone counter —
  ``key()`` folds the counter into ``jax.random.key(seed)``, and
  :meth:`key_for` additionally folds mesh coordinates so sharded
  programs draw independent yet reproducible streams per device
  (SURVEY.md §7 "Reproducible RNG across sharding").

Both streams are functions of ``(seed, counter)`` alone, so snapshots
capture them exactly.
"""

import contextlib

import numpy

from veles_tpu.distributable import Pickleable


class RandomGenerator(Pickleable):
    """Named reproducible RNG (ref: veles/prng/random_generator.py:64)."""

    def __init__(self, name="default", seed=None):
        self.name = name
        self._seed = 42 if seed is None else int(seed)
        self._counter = 0
        super(RandomGenerator, self).__init__()

    def init_unpickled(self):
        super(RandomGenerator, self).init_unpickled()
        self._np_ = None
        self._np_state = getattr(self, "_np_state", None)

    # -- seeding -------------------------------------------------------------

    @property
    def seed_value(self):
        return self._seed

    def seed(self, seed):
        """(Re)seed both streams (ref: random_generator.py:106)."""
        if isinstance(seed, str):
            seed = seed.encode()
        if isinstance(seed, numpy.ndarray):
            seed = seed.tobytes()
        if isinstance(seed, bytes):
            # hash, don't sum: entropy-file seeding must be order-sensitive
            import hashlib
            seed = int.from_bytes(
                hashlib.sha256(seed).digest()[:8], "little") % (1 << 63)
        self._seed = int(seed)
        self._counter = 0
        self._np_ = None
        self._np_state = None
        return self

    # -- host stream ---------------------------------------------------------

    @property
    def np(self):
        if self._np_ is None:
            self._np_ = numpy.random.Generator(
                numpy.random.PCG64(self._seed))
            if self._np_state is not None:
                self._np_.bit_generator.state = self._np_state
        return self._np_

    def __getstate__(self):
        if self._np_ is not None:
            self._np_state = self._np_.bit_generator.state
        return super(RandomGenerator, self).__getstate__()

    # numpy-facing API used by loaders / eager init
    def shuffle(self, arr):
        self.np.shuffle(arr)

    def permutation(self, n):
        return self.np.permutation(n)

    def randint(self, low, high=None, size=None):
        return self.np.integers(low, high, size=size)

    def rand(self, *shape):
        return self.np.random(shape)

    def normal(self, loc=0.0, scale=1.0, size=None):
        return self.np.normal(loc, scale, size)

    def fill(self, arr, vmin=-1.0, vmax=1.0):
        """Uniform fill of a numpy array in place
        (ref: random_generator.py:fill)."""
        arr[...] = self.np.uniform(vmin, vmax, arr.shape).astype(arr.dtype)

    def fill_normal(self, arr, mean=0.0, stddev=1.0):
        arr[...] = self.np.normal(mean, stddev, arr.shape).astype(arr.dtype)

    # -- device stream -------------------------------------------------------

    def key(self):
        """A fresh deterministic jax PRNG key; advances the counter."""
        import jax
        self._counter += 1
        return jax.random.fold_in(jax.random.key(self._seed), self._counter)

    def peek_key(self, offset=0):
        """The key the (offset+1)-th future :meth:`key` call would return,
        without advancing — ``peek_key(0)`` is the *next* draw (for traced
        loops that derive per-step keys with fold_in inside jit)."""
        import jax
        return jax.random.fold_in(
            jax.random.key(self._seed), self._counter + 1 + offset)

    def key_for(self, *folds):
        """A key with extra folds (e.g. mesh axis indices) so each shard
        draws an independent reproducible stream."""
        import jax
        k = self.key()
        for f in folds:
            k = jax.random.fold_in(k, int(f))
        return k

    # -- state capture (ref: veles/units.py:859-885) -------------------------

    @property
    def state(self):
        if self._np_ is not None:
            self._np_state = self._np_.bit_generator.state
        return {"seed": self._seed, "counter": self._counter,
                "np_state": self._np_state}

    @state.setter
    def state(self, value):
        self._seed = value["seed"]
        self._counter = value["counter"]
        self._np_state = value["np_state"]
        self._np_ = None

    @contextlib.contextmanager
    def preserve_state(self):
        """Run a block, then restore the RNG state — the reference wrapped
        ``initialize()`` with this so restored snapshots re-initialize
        identically."""
        saved = self.state
        try:
            yield self
        finally:
            self.state = saved


_generators = {}


def get(key="default"):
    """Process-wide named generator (ref: random_generator.py:289)."""
    gen = _generators.get(key)
    if gen is None:
        gen = _generators[key] = RandomGenerator(name=key)
    return gen
