"""veles_tpu — a TPU-native dataflow deep-learning framework.

A ground-up rebuild of the capabilities of Samsung Veles (reference:
/root/reference, see SURVEY.md) designed for TPU hardware: models are
Workflows — directed graphs of Units with control-flow gates and linked
attributes — whose accelerated segments compile into single XLA programs
via jax.jit, shard over device meshes with pjit/shard_map, and use Pallas
kernels for custom ops.

Top-level layout (mirrors SURVEY.md §1's layer map, TPU-first):

- :mod:`veles_tpu.config`        — ``root.*`` config tree (ref: veles/config.py)
- :mod:`veles_tpu.mutable`       — Bool gate algebra, LinkableAttribute (ref: veles/mutable.py)
- :mod:`veles_tpu.units`         — Unit graph nodes, gates, links (ref: veles/units.py)
- :mod:`veles_tpu.workflow`      — Workflow container + scheduler (ref: veles/workflow.py)
- :mod:`veles_tpu.backends`      — TPU / CPU device registry (ref: veles/backends.py)
- :mod:`veles_tpu.memory`        — Array over jax.Array + Watcher (ref: veles/memory.py)
- :mod:`veles_tpu.accelerated_units` — jit compilation layer (ref: veles/accelerated_units.py)
- :mod:`veles_tpu.ops`           — Pallas/XLA kernels (ref: cuda/, ocl/)
- :mod:`veles_tpu.loader`        — minibatch serving stack (ref: veles/loader/)
- :mod:`veles_tpu.models`        — NN layer/trainer units + model zoo (ref: Znicz surface)
- :mod:`veles_tpu.parallel`      — mesh, shardings, collectives (ref: veles/server.py et al.)
"""

__version__ = "0.1.0"

__all__ = ["__version__"]
