"""Global configuration tree.

TPU-native rebuild of the reference's attribute-autovivifying ``Config``
(ref: veles/config.py:60-152): settings live in a single global tree
``root.*``; reading a missing attribute creates a sub-tree, so user config
files can write ``root.mnist.learning_rate = 0.01`` without declarations.

Layered overrides (ref: veles/config.py:294-308): package defaults →
``/etc/default/veles_tpu`` → ``~/.veles_tpu`` → ``$PWD/site_config.py`` →
the per-run config file → ``-c "root.x=y"`` CLI snippets.
"""

import os
import runpy
from pathlib import Path


class Config:
    """A node in the config tree.  Attribute access autovivifies sub-trees."""

    def __init__(self, path="root"):
        object.__setattr__(self, "_path_", path)
        object.__setattr__(self, "_protected_", set())

    # -- tree behaviour ---------------------------------------------------

    def __getattr__(self, name):
        if name.startswith("__") and name.endswith("__"):
            raise AttributeError(name)
        child = Config("%s.%s" % (self._path_, name))
        object.__setattr__(self, name, child)
        return child

    def __setattr__(self, name, value):
        if name in self._protected_:
            raise AttributeError(
                "config key %s.%s is protected" % (self._path_, name))
        object.__setattr__(self, name, value)

    def protect(self, *names):
        """Mark keys read-only (ref: veles/config.py:79-84)."""
        self._protected_.update(names)

    def update(self, value):
        """Deep-merge a dict (or another Config) into this node."""
        if isinstance(value, Config):
            value = value.__content__()
        if not isinstance(value, dict):
            raise TypeError("Config.update() needs a dict, got %r" % (value,))
        for k, v in value.items():
            if k in self._protected_:
                raise AttributeError(
                    "config key %s.%s is protected" % (self._path_, k))
            if isinstance(v, dict):
                cur = vars(self).get(k)
                if not isinstance(cur, Config):
                    # a dict merge over a plain leaf replaces it with
                    # a fresh subtree (instead of crashing on
                    # None.update) — seeded from the leaf's own keys
                    # when the leaf was a plain dict, so layered
                    # overrides still MERGE rather than discard
                    node = Config("%s.%s" % (self._path_, k))
                    object.__setattr__(self, k, node)
                    if isinstance(cur, dict):
                        node.update(cur)
                getattr(self, k).update(v)
            else:
                setattr(self, k, v)
        return self

    def __content__(self):
        """The tree below this node as a plain nested dict."""
        out = {}
        for k, v in vars(self).items():
            if k.startswith("_") and k.endswith("_"):
                continue
            out[k] = v.__content__() if isinstance(v, Config) else v
        return out

    def get(self, name, default=None):
        """Read a key without autovivifying; Config-valued (unset) → default."""
        v = vars(self).get(name, default)
        return default if isinstance(v, Config) else v

    def get_dict(self, name, default=None):
        """Read a dict-valued key without autovivifying.  ``update``
        stores nested dicts AS subtrees, so plain ``get`` can't see
        them; this returns the subtree's content, a plain dict value,
        or ``default`` (for unset/None/empty)."""
        v = vars(self).get(name)
        if isinstance(v, Config):
            v = v.__content__()
        return dict(v) if v else default

    def __contains__(self, name):
        v = vars(self).get(name)
        return v is not None and not isinstance(v, Config)

    def __bool__(self):
        # An autovivified (empty) node is falsy so `if root.x.y:` is safe.
        return bool(self.__content__())

    def __iter__(self):
        return iter(self.__content__().items())

    def __repr__(self):
        return "Config(%s: %r)" % (self._path_, self.__content__())

    def print_(self, indent=0, file=None):
        import sys
        file = file or sys.stdout
        for k, v in sorted(vars(self).items()):
            if k.startswith("_") and k.endswith("_"):
                continue
            if isinstance(v, Config):
                print("  " * indent + k + ":", file=file)
                v.print_(indent + 1, file)
            else:
                print("  " * indent + "%s: %r" % (k, v), file=file)


#: The global configuration tree (ref: veles/config.py:152).
root = Config("root")

# -- package defaults (ref: veles/config.py:178-291) ----------------------

root.common.update({
    "dirs": {
        "cache": os.path.join(
            os.environ.get("XDG_CACHE_HOME", str(Path.home() / ".cache")),
            "veles_tpu"),
        "snapshots": os.path.join(os.getcwd(), "snapshots"),
        "datasets": os.environ.get(
            "VELES_TPU_DATA", os.path.join(os.getcwd(), "data")),
    },
    "precision": {
        # dtype policy: compute dtype for matmuls/convs, accumulation dtype,
        # parameter dtype (replaces the reference's dtype/PRECISION_LEVEL
        # macro layer, ocl/defines.cl:1-69).
        "compute_dtype": "bfloat16",
        "accum_dtype": "float32",
        "param_dtype": "float32",
        # 0 = default XLA; 1/2 map to jax.lax.Precision.HIGH/HIGHEST
        # (replaces Kahan/multipartial PRECISION_LEVEL knobs,
        # ocl/matrix_multiplication_precise.cl:1-46).
        "level": 0,
    },
    "engine": {
        "backend": os.environ.get("VELES_TPU_BACKEND", "auto"),
        # eager: skip jit entirely (debugging, like the reference's
        # numpy fallback); fuse: compile accelerated-unit chains into
        # one XLA program per segment (accelerated_units.py)
        "eager": False,
        "fuse": True,
    },
    "timings": False,
    # device mesh for StandardWorkflow sharding, e.g. {'dp': -1}
    # (models/standard.py); None = single device
    "mesh": None,
    # appended to snapshot file names (ensemble members set 'ens<N>')
    "snapshot_suffix": "",
    # fraction of the train set an ensemble member sees (None = all;
    # set per member by veles_tpu.ensemble)
    "ensemble_train_ratio": None,
    # compilation_cache_dir: persistent XLA compilation cache
    # (jax_compilation_cache_dir) — kills multi-second recompiles
    # across CLI runs; also settable with --compilation-cache
    "trace": {"run": False, "profiler_dir": None,
              "compilation_cache_dir": None},
    # asynchronous input pipeline (loader/prefetch.py): streaming
    # loaders decode batch k+1 and upload it while step k computes;
    # depth = batches prepared ahead (0 disables).  Falls back to the
    # synchronous path for master/slave serving and cross-process
    # meshes automatically.
    "loader": {"prefetch": {"enabled": True, "depth": 2}},
    # REST /generate resource caps (satellite of the input-pipeline
    # PR): oversize requests get a 400 instead of a giant alloc +
    # multi-second compile.  admin_token (also --admin-token) lets a
    # NON-loopback caller hit the admin endpoints (/drain, /shutdown)
    # with "Authorization: Bearer <token>" — unset, they stay
    # loopback-only
    # model_id is the name the OpenAI facade (/v1/models,
    # /v1/completions) serves the chain under
    "api": {"max_steps": 2048, "max_batch": 64, "admin_token": None,
            "model_id": "veles-lm"},
    # multi-replica fleet router (serving/router.py): health-aware
    # load balancing over N engine replicas with per-replica circuit
    # breakers (closed -> open after breaker_failures consecutive
    # failures, half-open single-probe recovery after
    # breaker_cooldown), capped-exponential retry backoff with jitter
    # (retry_delay base, retry_cap cap, retries total attempts, never
    # past the request deadline), straggler hedging for idempotent
    # requests (hedge_delay seconds; 0 disables), prompt-prefix
    # session affinity (first affinity_tokens tokens; 0 disables) and
    # fleet-level shedding (503 + shed_retry_after once no replica is
    # eligible).  request_timeout None defers to
    # root.common.serving.request_timeout.
    "router": {
        "health_interval": 0.5,
        "health_timeout": 1.0,
        "breaker_failures": 3,
        "breaker_cooldown": 2.0,
        "retries": 3,
        "retry_delay": 0.05,
        "retry_cap": 2.0,
        "hedge_delay": 0.0,
        "affinity_tokens": 16,
        "request_timeout": None,
        "shed_retry_after": 2,
        # cache-topology routing (PR 19): prefix_routing routes
        # single-row /generate bodies to the replica advertising the
        # longest resident prefix (falls back to crc32 affinity when
        # nobody is warm); prefix_fetch additionally SHIPS a peer's
        # longer resident prefix onto the chosen replica over the
        # binary KV wire before forwarding, when the peer leads by
        # at least prefix_fetch_min blocks (best-effort — failures
        # admit cold and count prefix_peer_fetch_fails)
        "prefix_routing": True,
        "prefix_fetch": True,
        "prefix_fetch_min": 2,
    },
    # host-side instrumentation (per-unit spans + metric histograms,
    # veles_tpu/telemetry/) — on by default, overhead-gated in CI.
    # cost_analysis: capture XLA cost/memory analysis once per jitted
    # entry point (one extra AOT compile each; degrades to Nones when
    # the backend can't report)
    "telemetry": {"enabled": True, "cost_analysis": True},
    # training-health monitor (telemetry/health.py): policy is what
    # happens on a NaN/Inf step — warn | skip_step (drop the update
    # in-graph) | halt (stop the workflow, keep the process up)
    "health": {
        "enabled": True,
        "policy": "warn",
        "grad_norm_max": None,
        "sync_every": 1,
        "ema_beta": 0.9,
        "divergence_tolerance": 1.5,
        "divergence_patience": 3,
    },
    # crash flight recorder (telemetry/flight_recorder.py): bundle
    # lands in `dir` (default: the snapshot dir) on crash/SIGUSR1
    "flightrec": {"enabled": True, "dir": None, "dump_on_exit": False},
    # alerting engine (telemetry/alerts.py): a low-frequency ticker
    # evaluates declarative rules over the metrics registry with a
    # pending -> firing -> resolved state machine and for_seconds
    # hold-downs.  `defaults` ships the built-in rule set (SLO burn
    # fast+slow, breaker open, health halt, replica unreachable, KV
    # pressure, watchdog stall, prefix-hit collapse, padding waste);
    # `rules` appends user rules as dicts — {"name", "expr", "for",
    # "severity"} with expr = "[func(]family[{k=v}][)] OP number"
    # (see docs/observability.md for the grammar).  webhook_url gets
    # a JSON POST per fire/resolve (best-effort sink, fault point
    # `alerts.webhook`); router and serving replicas each run one
    # engine when enabled, served at GET /alerts
    "alerts": {
        "enabled": True,
        "interval": 1.0,
        "defaults": True,
        "rules": (),
        "webhook_url": None,
    },
    # per-request distributed tracing (telemetry/reqtrace.py): trace
    # ids minted at the edge (or accepted via X-Veles-Trace),
    # propagated router -> replica -> scheduler, phase spans appended
    # to the JSONL event sink.  ON by default; overhead is gated in
    # tier-1 (<5%, the tracing_overhead marker).  Disabling stops the
    # span emission only — ids still mint and echo, so client-side
    # correlation keeps working
    "reqtrace": {"enabled": True},
    # serving SLOs (serving/metrics.py SLOTracker): per-priority-class
    # latency objectives in ms — ttft_ms gates submit->first-token at
    # the replica, e2e_ms gates whole-request time (replica-side AND
    # the router's all-attempts fleet tail); None disables a class.
    # target is the success ratio whose complement is the error
    # budget; windows are the trailing burn-rate horizons in seconds
    # (multi-window: pair a fast window for paging with a slow one
    # for ticketing).  Exported as the veles_slo_* families and the
    # "slo" block of /serving/metrics and /router/state
    "slo": {
        "enabled": True,
        "target": 0.99,
        "windows": (60.0, 300.0, 3600.0),
        "ttft_ms": {"low": 5000.0, "normal": 2000.0, "high": 500.0},
        "e2e_ms": {"low": 120000.0, "normal": 60000.0,
                   "high": 30000.0},
    },
    # continuous-batching serving knobs (serving/scheduler.py):
    # kv "paged"|"dense"; kv_blocks None derives the dense-equivalent
    # pool (max_slots * ceil(window / block_size)); prefill_chunk 0
    # disables chunked prefill; request_timeout is the whole-request
    # deadline in seconds (queued + decoding; 0 disables); watchdog is
    # the stuck-decode-loop detector threshold in seconds (0 disables
    # — keep it far above the worst first-compile stall);
    # shed_block_factor sheds new submits (503) once the queue's
    # committed block budget exceeds factor x kv_blocks (0 disables);
    # spec enables speculative decoding (n-gram prompt-lookup drafts
    # + one batched verify pass; spec_k tokens drafted per slot,
    # output streams bit-identical to spec-off); prefix_cache enables
    # the cross-request radix prefix cache over the paged block pools
    # (warm prompts skip prefill for resident leading blocks) with
    # prefix_evict allowing LRU eviction of refcount-0 resident
    # blocks under admission pressure.  Both DEFAULT ON since the
    # PR 10 mixed-priority soak (the "after real-traffic soak" gate
    # PR 9 left open): streams are bit-identical either way, so the
    # knobs are opt-OUT (spec needs a verify-capable chain and
    # prefix_cache needs chunked prefill + a pow2 block size — the
    # scheduler falls back automatically when unsupported)
    # kv_dtype "fp32" keeps the compute-dtype pools (bit-parity
    # baseline); "int8" stores the paged K/V pools quantized with
    # per-row scales beside the block tables — ~half the bytes per
    # cached token, so the same kv_blocks HBM budget decodes ~2x the
    # concurrent streams (quality-gated: serving/kv_quality.py +
    # quality.py kv_quant record).  fused_verify scores the
    # speculative run in ONE pass (no scatter-then-gather round
    # trip); it is allclose rather than bit-identical to the
    # two-pass verify, so the fp32 parity baseline keeps it OFF
    # (int8 pools always verify fused)
    # tp shards the jitted serving steps over a {"tp": N} mesh
    # (Megatron column/row weight splits, head-wise paged K/V pools
    # — per-chip kv_blocks HBM drops by the factor; serving/tp.py);
    # 0 disables.  role disaggregates prefill from decode across a
    # fleet: "prefill" replicas chunk-prefill and export finished KV
    # blocks (GET /serving/kv_export/<handle>), "decode" replicas
    # import them (POST /serving/kv_import) and run the decode loop;
    # "both" (default) keeps the colocated single-replica shape.
    "serving": {
        "tp": 0,
        "role": "both",
        "kv": "paged",
        "block_size": 16,
        "kv_blocks": None,
        "kv_dtype": "fp32",
        "fused_verify": False,
        "prefill_chunk": 64,
        "warm_buckets": True,
        "request_timeout": 120.0,
        "watchdog": 300.0,
        "shed_block_factor": 4.0,
        "spec": True,
        "spec_k": 4,
        "prefix_cache": True,
        "prefix_evict": True,
        # tiered KV (PR 19): kv_host_bytes > 0 arms the host-RAM
        # overflow tier — prefix blocks evicted from the device trie
        # demote into host buffers (byte-budgeted, LRU) and promote
        # back when a matching prompt admits; 0 disables (evictions
        # discard, the pre-tier behavior).  kv_export_bytes caps the
        # TOTAL bytes parked in pending disagg KV exports (oldest
        # records expire first once over), replacing the old flat
        # 64-record cap — a byte budget tracks the actual HBM-sized
        # payloads a prefill replica holds for its decode peers
        "kv_host_bytes": 0,
        "kv_export_bytes": 256 << 20,
        # model-based drafting (PR 20): drafter "model" arbitrates a
        # Medusa-style draft head (serving/draft.py, conditioned on
        # the engine's hidden-state lane) against the free n-gram
        # proposer per slot by accept-rate EMA; "ngram" (default)
        # keeps the self-speculative baseline — either way the
        # emitted streams are bit-identical to spec off, drafting
        # moves throughput only.  The EMA controller adapts each
        # slot's draft length between draft_k_min and spec_k along
        # the warmed power-of-two verify buckets: blend weight
        # draft_ema, halve below draft_shrink, double above
        # draft_grow.  tp_overlap swaps the GSPMD-partitioned tp
        # step for an explicit shard_map step whose row-parallel
        # all-reduces are expressed per shard (collective-permute at
        # tp=2), letting XLA schedule the combine against the
        # residual/LN compute — fp32 pools only (int8 per-row scales
        # need full-row amax), bit-identical to the GSPMD step.
        "drafter": "ngram",
        "draft_k_min": 1,
        "draft_ema": 0.5,
        "draft_shrink": 0.5,
        "draft_grow": 0.8,
        "tp_overlap": False,
    },
    # replica supervision (serving/fleet.py): rebalance lets a
    # disaggregated fleet re-role replicas when a whole role pool
    # loses its last live member — a respawn fills the empty pool
    # instead of its own (when its own keeps a member), and the
    # monitor restarts a surplus replica into a pool no respawn is
    # filling.  Off, a dead pool stays dead until a human re-roles
    # the fleet (the pre-rebalance behavior).
    "fleet": {"rebalance": True},
    # fleet control plane (serving/controller.py): a FleetController
    # loop on the router host closes three loops — replica count
    # (scale up on the fast+slow SLO-burn pair or queue pressure,
    # scale down via drain when both windows are quiet), the
    # prefill:decode role ratio (prefill queue wait vs decode slot
    # occupancy, moved through Fleet.restart_as), and KV knobs
    # (shed_block_factor nudges via POST /serving/tune, kv_blocks
    # recommendations as audit events only).  Off by default: the
    # controller only ever acts when an operator arms it.
    # Hysteresis: scale-up needs the burn pair OR mean queue depth
    # >= queue_high; scale-down needs quiet_ticks consecutive calm
    # ticks AND mean slot occupancy <= occupancy_low, and each
    # direction honors its own cooldown.  role_deadband is the
    # minimum normalized pressure gap before a re-role fires.
    "controller": {
        "enabled": False,
        "interval": 2.0,
        "min_replicas": 1,
        "max_replicas": 4,
        "scale_up_cooldown": 10.0,
        "scale_down_cooldown": 30.0,
        "quiet_ticks": 5,
        "queue_high": 4.0,
        "occupancy_low": 0.3,
        "role_deadband": 0.25,
        "kv_pressure_high": 0.85,
        "kv_pressure_low": 0.5,
        "shed_step": 0.5,
        "shed_min": 1.0,
        "shed_max": 8.0,
        "audit_keep": 256,
        "history_window": 30.0,
    },
    # per-tenant admission economics (tenant/admission.py): the
    # router resolves a tenant id from the auth header (hash of the
    # bearer token, or X-Veles-Tenant on loopback) and tags every
    # request with it; with enabled=True it also enforces a
    # per-tenant token bucket (rate tokens/s, burst capacity;
    # exceeding it is a structured 429 + Retry-After) and a
    # weighted-fair concurrency lane (max_concurrent in-flight
    # requests per tenant, 0 = no cap) so a flooding tenant degrades
    # only itself.  label_cardinality bounds the metrics label: the
    # first N distinct tenants keep their own label value, the rest
    # report as "other".
    "tenant": {
        "enabled": False,
        "rate": 0.0,
        "burst": 0.0,
        "max_concurrent": 0,
        "label_cardinality": 8,
    },
    # embedded time-series store (telemetry/tsdb.py): a background
    # ticker samples the metrics registry (replicas) or the federated
    # fleet merge (router) into downsampling tiers of
    # (step_s, retention_s) ring buffers — counters as per-bucket
    # deltas so rates are exact across tier boundaries, gauges as
    # (count, sum, min, max, last) aggregates.  Queryable via
    # GET /metrics/history and TimeSeriesStore.range(); feeds the
    # *_over_time/deriv/drop_vs_baseline alert functions, the
    # controller's history windows and the dashboard sparklines.
    # max_series caps distinct stored series (later arrivals are
    # dropped + counted); max_bytes is the estimated-allocation
    # budget (least-recently-updated whole series evicted when
    # exceeded).  metering gates the scheduler's per-tenant usage
    # attribution (veles_tenant_usage_* families + /tenants/usage)
    # — separate knob so the on-vs-off overhead soak can isolate it.
    "tsdb": {
        "enabled": True,
        "tiers": ((1.0, 600.0), (10.0, 3600.0), (60.0, 86400.0)),
        "max_series": 512,
        "max_bytes": 16 << 20,
        "metering": True,
    },
    # fault injection (veles_tpu/faults/): spec string parsed on first
    # fire(), same grammar as the VELES_FAULTS env var —
    # "point=action[:arg][@after][xtimes][~key];..." (empty = unarmed)
    "faults": {"spec": ""},
    # status dashboard bind address (web_status.py) and the
    # status_url a Launcher pushes run updates to (None = don't)
    "web": {"host": "localhost", "port": 8090, "status_url": None},
    # live matplotlib graphics service (launcher --graphics)
    "graphics": {"enabled": False, "port": 0},
    # report publishing backends; keys under `confluence` are
    # site-supplied (server/space/token/...) — an OPEN config subtree
    "publishing": {"confluence": {}},
})
root.common.protect("dirs")


def _exec_globals():
    g = {"root": root, "Config": Config}
    try:  # genetics tuneables are first-class config values
        from veles_tpu.genetics import Choice, Range
        g["Range"] = Range
        g["Choice"] = Choice
    except ImportError:  # pragma: no cover
        pass
    return g


def apply_config_file(path, extra_globals=None):
    """Execute a per-run config file: plain Python mutating ``root``
    (ref: veles/__main__.py:436-438)."""
    g = _exec_globals()
    if extra_globals:
        g.update(extra_globals)
    runpy.run_path(path, init_globals=g)


def apply_override(snippet):
    """Apply a ``-c "root.x.y = z"`` CLI override
    (ref: veles/__main__.py:474-481)."""
    exec(snippet, _exec_globals())


def load_site_configs():
    """Merge layered site overrides (ref: veles/config.py:294-308)."""
    for p in ("/etc/default/veles_tpu",
              str(Path.home() / ".veles_tpu"),
              os.path.join(os.getcwd(), "site_config.py")):
        if os.path.isfile(p):
            try:
                runpy.run_path(p, init_globals={"root": root})
            except Exception:  # site files must never break startup
                import logging
                logging.getLogger("config").exception(
                    "failed to apply site config %s", p)


def get(cfg, default=None):
    """``get(root.x.y, default)`` — unset (Config) values become default."""
    return default if isinstance(cfg, Config) else cfg
