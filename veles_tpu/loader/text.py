"""Text corpus loading for language models: a trainable byte-level
BPE vocabulary + a full-batch window loader.

No reference analogue (the reference had no sequence models and no
text pipeline at all — SURVEY.md §5); this closes the practical LM
loop: point ``samples/lm.py`` at a text file and it trains on it
end-to-end (``root.lm_tpu.text_path``), then decodes back to text
through the same vocabulary.

Byte-level BPE: the base alphabet is all 256 bytes, so ANY input
encodes without unknown tokens; merges are learned over
whitespace-delimited chunks (each chunk keeps its trailing
whitespace, so a detokenized stream round-trips exactly).  Optional
``specials`` reserve ids right after the byte alphabet — the encoder
never emits them; they exist for the caller (``<eos>`` pairs with
``generate(stop_token=vocab.special("<eos>"))``).
"""

import collections
import json

import numpy

from veles_tpu.loader.fullbatch import FullBatchLoader


def _chunks(text):
    """Whitespace-keeping pre-tokenization: every chunk is a word plus
    its trailing whitespace, so concat(chunks) == text exactly."""
    out, start = [], 0
    n = len(text)
    i = 0
    while i < n:
        while i < n and not text[i].isspace():
            i += 1
        while i < n and text[i].isspace():
            i += 1
        out.append(text[start:i])
        start = i
    return out


class BytePairVocab:
    """Byte-level BPE vocabulary: ids 0..255 are raw bytes, then
    ``specials``, then learned merges (rank order)."""

    #: bound on the per-chunk encode memo (LRU): a long-lived server
    #: encoding diverse text must not grow the cache without limit
    CACHE_LIMIT = 65536

    def __init__(self, merges, specials=()):
        #: merge list [(left_id, right_id)] in rank order; merged
        #: token i gets id base + i
        self.merges = [tuple(m) for m in merges]
        self.specials = tuple(specials)
        self._special_ids = {s: 256 + i
                             for i, s in enumerate(self.specials)}
        base = 256 + len(self.specials)
        self._ranks = {m: i for i, m in enumerate(self.merges)}
        self._merged_id = {m: base + i for i, m in enumerate(self.merges)}
        #: id → bytes (specials decode to b"")
        toks = [bytes([i]) for i in range(256)]
        toks += [b"" for _ in self.specials]
        for left, right in self.merges:
            toks.append(toks[left] + toks[right])
        self._bytes = toks
        self._cache = collections.OrderedDict()

    # -- construction --------------------------------------------------------

    @classmethod
    def train(cls, text, vocab_size, specials=(), min_freq=2):
        """Learn merges on ``text`` until the vocab reaches
        ``vocab_size`` (or no pair clears ``min_freq``).

        Pair counts are maintained INCREMENTALLY: each merge still
        scans the chunk vocabulary for containment (O(unique chunks)
        per merge), but only the words that actually contain the
        merged pair are re-tokenized and have their pair counts
        adjusted — far cheaper than a full corpus re-count per merge,
        so training a 512-token vocab on a multi-megabyte corpus
        stays seconds."""
        base = 256 + len(specials)
        if vocab_size < base:
            raise ValueError(
                "vocab_size %d < %d (256 bytes + %d specials)"
                % (vocab_size, base, len(specials)))
        freqs = collections.Counter(_chunks(text))
        words = {w: tuple(w.encode("utf-8")) for w in freqs}
        pair_counts = collections.Counter()
        for w, f in freqs.items():
            seq = words[w]
            for a, b in zip(seq, seq[1:]):
                pair_counts[(a, b)] += f
        merges = []
        while base + len(merges) < vocab_size and pair_counts:
            pair, count = pair_counts.most_common(1)[0]
            if count < min_freq:
                break
            new_id = base + len(merges)
            merges.append(pair)
            for w, f in freqs.items():
                seq = words[w]
                if len(seq) < 2:
                    continue
                # fast containment scan before any rebuilding
                hit = False
                for i in range(len(seq) - 1):
                    if seq[i] == pair[0] and seq[i + 1] == pair[1]:
                        hit = True
                        break
                if not hit:
                    continue
                for a, b in zip(seq, seq[1:]):
                    pair_counts[(a, b)] -= f
                out, i = [], 0
                while i < len(seq):
                    if i + 1 < len(seq) and (seq[i], seq[i + 1]) == pair:
                        out.append(new_id)
                        i += 2
                    else:
                        out.append(seq[i])
                        i += 1
                words[w] = tuple(out)
                for a, b in zip(out, out[1:]):
                    pair_counts[(a, b)] += f
            pair_counts = +pair_counts  # drop zero/negative entries
        return cls(merges, specials)

    # -- io ------------------------------------------------------------------

    def save(self, path):
        with open(path, "w") as f:
            json.dump({"merges": self.merges,
                       "specials": list(self.specials)}, f)

    @classmethod
    def load(cls, path):
        with open(path) as f:
            d = json.load(f)
        return cls(d["merges"], d.get("specials", ()))

    # -- encoding ------------------------------------------------------------

    @property
    def size(self):
        return 256 + len(self.specials) + len(self.merges)

    def special(self, name):
        return self._special_ids[name]

    def _encode_chunk(self, chunk):
        ids = self._cache.get(chunk)
        if ids is not None:
            self._cache.move_to_end(chunk)
            return ids
        seq = list(chunk.encode("utf-8"))
        while len(seq) > 1:
            # merge the lowest-rank pair present (standard BPE encode)
            best, best_rank = None, None
            for a, b in zip(seq, seq[1:]):
                r = self._ranks.get((a, b))
                if r is not None and (best_rank is None or r < best_rank):
                    best, best_rank = (a, b), r
            if best is None:
                break
            nid = self._merged_id[best]
            out, i = [], 0
            while i < len(seq):
                if i + 1 < len(seq) and (seq[i], seq[i + 1]) == best:
                    out.append(nid)
                    i += 2
                else:
                    out.append(seq[i])
                    i += 1
            seq = out
        self._cache[chunk] = seq
        if len(self._cache) > self.CACHE_LIMIT:
            self._cache.popitem(last=False)  # evict least-recent
        return seq

    def encode(self, text):
        """text → list of ids (never emits specials; no unknowns —
        the byte alphabet covers everything)."""
        ids = []
        for chunk in _chunks(text):
            ids.extend(self._encode_chunk(chunk))
        return ids

    def decode(self, ids):
        """ids → text (specials decode to nothing; invalid utf-8 from
        a truncated window decodes with replacement)."""
        return b"".join(self._bytes[int(i)]
                        for i in ids).decode("utf-8", "replace")


class FullBatchTextLM(FullBatchLoader):
    """Sliding windows of BPE token ids over a text corpus —
    ``[n_windows, seq_len]`` int32, ready for ``loss="next_token"``.

    The vocabulary is trained on the corpus itself unless one is
    passed in (``vocab=``) or loadable from ``vocab_path``.  Windows
    are laid out valid-first (``class_lengths`` convention: test,
    valid, train), with the validation share taken from the corpus
    TAIL so it is never seen in training windows."""

    def __init__(self, workflow, path=None, text=None, vocab=None,
                 vocab_path=None, vocab_size=512, seq_len=64,
                 stride=None, valid_fraction=0.1, specials=("<eos>",),
                 **kwargs):
        super(FullBatchTextLM, self).__init__(workflow, **kwargs)
        if (path is None) == (text is None):
            raise ValueError("pass exactly one of path= or text=")
        self.path = path
        self.text = text
        self.vocab = vocab
        self.vocab_path = vocab_path
        self.vocab_size = int(vocab_size)
        self.seq_len = int(seq_len)
        self.stride = int(stride) if stride else int(seq_len)
        self.valid_fraction = float(valid_fraction)
        self.specials = tuple(specials)

    def load_data(self):
        text = self.text
        if text is None:
            with open(self.path, encoding="utf-8") as f:
                text = f.read()
        if self.vocab is None:
            import os
            if self.vocab_path and os.path.exists(self.vocab_path):
                self.vocab = BytePairVocab.load(self.vocab_path)
            else:
                self.vocab = BytePairVocab.train(
                    text, self.vocab_size, specials=self.specials)
                if self.vocab_path:
                    # persist the artifact: decoding a served model's
                    # token replies needs this file client-side
                    self.vocab.save(self.vocab_path)
        ids = numpy.asarray(self.vocab.encode(text), numpy.int32)
        if ids.size < self.seq_len + 1:
            raise ValueError(
                "corpus shorter than one %d-token window" % self.seq_len)

        def windows(stream):
            if stream.size < self.seq_len:
                return numpy.zeros((0, self.seq_len), numpy.int32)
            starts = range(0, stream.size - self.seq_len + 1,
                           self.stride)
            return numpy.stack([stream[s:s + self.seq_len]
                                for s in starts])

        if self.valid_fraction > 0:
            # split the TOKEN STREAM before windowing: overlapping
            # windows across the boundary would leak training tokens
            # into validation when stride < seq_len
            n_valid_tok = max(self.seq_len,
                              int(round(ids.size * self.valid_fraction)))
            split = ids.size - n_valid_tok
            if split < self.seq_len:
                raise ValueError(
                    "corpus too small for the requested split")
            train_w = windows(ids[:split])
            valid_w = windows(ids[split:])
        else:
            train_w = windows(ids)
            valid_w = numpy.zeros((0, self.seq_len), numpy.int32)
        # layout is valid-first (test, valid, train convention)
        self.original_data = numpy.concatenate([valid_w, train_w])
        self.class_lengths[:] = [0, len(valid_w), len(train_w)]
        self.original_labels = [0] * (len(valid_w) + len(train_w))
