"""Minibatch stream save / replay (rebuild of veles/loader/saver.py:69,182).

``MinibatchesSaver`` is a unit placed after a loader that appends every
served minibatch to a compressed pickle stream; ``MinibatchesLoader``
replays such a file as a Loader — the reference used this to freeze an
augmented/shuffled data stream and to feed workers without the original
dataset.
"""

import gzip
import pickle

import numpy

from veles_tpu.loader.base import Loader, TRAIN
from veles_tpu.units import Unit


class MinibatchesSaver(Unit):
    """Appends (class, size, data, labels) per run
    (ref: loader/saver.py:69)."""

    VIEW_GROUP = "SERVICE"

    def __init__(self, workflow, path="minibatches.pickle.gz", **kwargs):
        super(MinibatchesSaver, self).__init__(workflow, **kwargs)
        self.path = path
        self.loader = None
        self.demand("loader")

    def init_unpickled(self):
        super(MinibatchesSaver, self).init_unpickled()
        self._file_ = None

    def initialize(self, **kwargs):
        super(MinibatchesSaver, self).initialize(**kwargs)
        self._file_ = gzip.open(self.path, "wb")
        pickle.dump(
            {"max_minibatch_size": self.loader.max_minibatch_size,
             "data_shape": tuple(self.loader.minibatch_data.shape[1:]),
             "data_dtype": str(self.loader.minibatch_data.dtype)},
            self._file_)

    def run(self):
        l = self.loader
        l.minibatch_data.map_read()
        l.minibatch_labels.map_read()
        pickle.dump(
            (l.minibatch_class, l.minibatch_size,
             numpy.array(l.minibatch_data.mem[:l.minibatch_size]),
             numpy.array(l.minibatch_labels.mem[:l.minibatch_size])),
            self._file_)

    def stop(self):
        if self._file_ is not None:
            self._file_.close()
            self._file_ = None


class MinibatchesLoader(Loader):
    """Replays a saved minibatch stream (ref: loader/saver.py:182).

    The stream is read fully at initialize (it was minibatch-sized to fit
    memory budgets) and served as a regular class-partitioned dataset.
    """

    def __init__(self, workflow, path="minibatches.pickle.gz", **kwargs):
        super(MinibatchesLoader, self).__init__(workflow, **kwargs)
        self.path = path

    def load_data(self):
        chunks = {0: [], 1: [], 2: []}
        labels = {0: [], 1: [], 2: []}
        with gzip.open(self.path, "rb") as f:
            header = pickle.load(f)
            self.max_minibatch_size = header["max_minibatch_size"]
            want_shape = tuple(header["data_shape"])
            want_dtype = header["data_dtype"]
            while True:
                try:
                    ci, size, data, lbls = pickle.load(f)
                except EOFError:
                    break
                if tuple(data.shape[1:]) != want_shape \
                        or str(data.dtype) != want_dtype:
                    raise ValueError(
                        "corrupt minibatch stream %s: chunk %s/%s vs "
                        "header %s/%s" % (self.path, data.shape[1:],
                                          data.dtype, want_shape,
                                          want_dtype))
                chunks[ci].append(data[:size])
                labels[ci].append(lbls[:size])
        datas, lbl_list = [], []
        for ci in (0, 1, 2):
            if chunks[ci]:
                arr = numpy.concatenate(chunks[ci], axis=0)
                self.class_lengths[ci] = len(arr)
                datas.append(arr)
                lbl_list.extend(numpy.concatenate(labels[ci]).tolist())
            else:
                self.class_lengths[ci] = 0
        self._data = numpy.concatenate(datas, axis=0)
        self._labels = numpy.asarray(lbl_list, numpy.int32)

    def create_minibatch_data(self):
        shape = (self.max_minibatch_size,) + self._data.shape[1:]
        self.minibatch_data.reset(numpy.zeros(shape, self._data.dtype))

    def fill_minibatch(self):
        size = self.minibatch_size
        idx = self.minibatch_indices.mem[:size]
        self.minibatch_data.mem[:size] = self._data[idx]
        self.minibatch_labels.mem[:size] = self._labels[idx]

    def iterate_train(self):
        lo = self.class_end_offsets[1]
        hi = self.class_end_offsets[TRAIN]
        yield self._data[lo:hi], None
