"""Image loaders — decode / scale / crop / mirror / color-space +
label-from-path (rebuild of veles/loader/image.py:106,
loader/file_image.py:53, loader/fullbatch_image.py:56).

The reference decoded with PIL on the host and augmented per minibatch;
the TPU-native split keeps ALL decode/augment work on the host (numpy +
PIL — the TPU sees only ready float32 tensors) and offers two serving
modes:

- :class:`FileImageLoader` — streaming: decodes the minibatch's files on
  demand (datasets larger than RAM);
- :class:`FullBatchFileImageLoader` — materializes every image once at
  ``load_data`` time into the HBM-resident ``FullBatchLoader`` dataset,
  so training inherits the one-dispatch span-serving fast path.

Label-from-path follows the reference's convention: the parent directory
name is the label unless :meth:`get_image_label` is overridden
(ref: file_loader.py label-from-dir behavior).
"""

import os
import re

import numpy

from veles_tpu.loader.base import Loader, TEST, VALID, TRAIN
from veles_tpu.loader.fullbatch import FullBatchLoader, FullBatchLoaderMSE

try:  # PIL is present in this image; gate anyway (zero-install rule)
    from PIL import Image
    HAS_PIL = True
except ImportError:  # pragma: no cover
    HAS_PIL = False

#: extensions FileImageLoaderBase scans for (ref: image.py MODE_* lists)
IMAGE_EXTENSIONS = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".tif",
                    ".tiff", ".ppm", ".webp", ".npy")


class ImagePipeline(object):
    """The shared decode → color-space → scale → crop → mirror pipeline
    (ref: image.py:106 scale/crop/mirror/color-space attrs).

    All transforms are host-side numpy/PIL; output is float32 HWC in
    [0, 1] (uint8 sources) ready for device upload.
    """

    def __init__(self, color_space="RGB", scale=None,
                 scale_maintain_aspect_ratio=False, crop=None,
                 mirror=False, rotation=None, add_sobel=False,
                 prng=None):
        #: "RGB" | "GRAY" — PIL mode conversion target
        self.color_space = color_space
        #: (width, height) to scale to, or a float ratio, or None
        self.scale = scale
        self.scale_maintain_aspect_ratio = scale_maintain_aspect_ratio
        #: (width, height) crop window, or None
        self.crop = crop
        #: False | True (always flip) | "random"
        self.mirror = mirror
        #: rotation augmentation (ref: veles/loader/image.py rotate
        #: support): a fixed angle in degrees, or (lo, hi) sampled per
        #: train image, or None
        self.rotation = rotation
        # silently skipping a configured RANDOM augmentation would be a
        # lie — every sampling transform needs the sampler.  (crop
        # without a prng is fine: center crop is its defined
        # deterministic/eval semantic.)
        if prng is None:
            if isinstance(rotation, (tuple, list)):
                raise ValueError("ranged rotation requires a prng")
            if mirror == "random":
                raise ValueError('mirror="random" requires a prng')
        #: append a Sobel gradient-magnitude channel (ref: image.py
        #: add_sobel — the reference used OpenCV; 2 numpy convolutions
        #: suffice)
        self.add_sobel = add_sobel
        self.prng = prng

    # -- steps -----------------------------------------------------------------

    def decode(self, path):
        """File → numpy HWC uint8/float array."""
        if path.endswith(".npy"):
            return numpy.load(path)
        if not HAS_PIL:  # pragma: no cover
            raise RuntimeError("PIL unavailable — cannot decode %s" % path)
        img = Image.open(path)
        mode = "L" if self.color_space in ("GRAY", "L") else "RGB"
        if img.mode != mode:
            img = img.convert(mode)
        arr = numpy.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr

    def _scale(self, arr):
        if self.scale is None:
            return arr
        h, w = arr.shape[:2]
        if isinstance(self.scale, float):
            tw, th = int(round(w * self.scale)), int(round(h * self.scale))
        else:
            tw, th = self.scale
        if (w, h) == (tw, th):
            return arr
        if self.scale_maintain_aspect_ratio:
            # fit inside (tw, th), pad with zeros (ref: image.py
            # background fill on aspect-preserving scale)
            ratio = min(tw / w, th / h)
            sw, sh = int(round(w * ratio)), int(round(h * ratio))
            resized = self._resize(arr, sw, sh)
            out = numpy.zeros((th, tw) + arr.shape[2:], arr.dtype)
            y0, x0 = (th - sh) // 2, (tw - sw) // 2
            out[y0:y0 + sh, x0:x0 + sw] = resized
            return out
        return self._resize(arr, tw, th)

    @staticmethod
    def _resize(arr, tw, th):
        if HAS_PIL and arr.dtype == numpy.uint8:
            img = Image.fromarray(arr.squeeze() if arr.shape[2] == 1
                                  else arr)
            out = numpy.asarray(img.resize((tw, th), Image.BILINEAR))
            if out.ndim == 2:
                out = out[:, :, None]
            return out
        # nearest-neighbour fallback for float/npy sources
        h, w = arr.shape[:2]
        yi = numpy.clip((numpy.arange(th) * h / th).astype(int), 0, h - 1)
        xi = numpy.clip((numpy.arange(tw) * w / tw).astype(int), 0, w - 1)
        return arr[yi][:, xi]

    def _crop(self, arr, random):
        if self.crop is None:
            return arr
        cw, ch = self.crop
        h, w = arr.shape[:2]
        if h < ch or w < cw:
            raise ValueError("crop %s exceeds image %s" %
                             ((cw, ch), (w, h)))
        if random and self.prng is not None:
            y0 = int(self.prng.randint(0, h - ch + 1))
            x0 = int(self.prng.randint(0, w - cw + 1))
        else:
            y0, x0 = (h - ch) // 2, (w - cw) // 2
        return arr[y0:y0 + ch, x0:x0 + cw]

    def _rotate(self, arr, random):
        if self.rotation is None:
            return arr
        if isinstance(self.rotation, (tuple, list)):
            if not random:
                return arr  # ranged rotation is a train-time augment
            lo, hi = self.rotation
            angle = float(lo) + float(self.prng.rand()) * \
                (float(hi) - float(lo))
        else:
            angle = float(self.rotation)
        if not angle:
            return arr
        if HAS_PIL and arr.dtype == numpy.uint8:
            squeeze = arr.shape[2] == 1
            img = Image.fromarray(arr.squeeze() if squeeze else arr)
            out = numpy.asarray(img.rotate(
                angle, resample=Image.BILINEAR))
            if out.ndim == 2:
                out = out[:, :, None]
            return out
        # float/npy fallback: right-angle steps only (arbitrary-angle
        # float interpolation isn't worth hand-rolling here) — a
        # configured angle that can't be honored must fail loudly, not
        # silently round
        if angle % 90.0:
            raise ValueError(
                "rotation=%s needs PIL + uint8 input; float/npy "
                "sources support multiples of 90 only" % angle)
        k = int(angle / 90.0) % 4
        return numpy.rot90(arr, k) if k else arr

    def _mirror(self, arr, random):
        if not self.mirror:
            return arr
        if self.mirror == "random":
            if not random or self.prng is None \
                    or self.prng.randint(0, 2) == 0:
                return arr
        return arr[:, ::-1]

    def _sobel(self, arr):
        if not self.add_sobel:
            return arr
        gray = arr.mean(axis=2)
        gx = numpy.zeros_like(gray)
        gy = numpy.zeros_like(gray)
        gx[:, 1:-1] = gray[:, 2:] - gray[:, :-2]
        gy[1:-1, :] = gray[2:, :] - gray[:-2, :]
        mag = numpy.sqrt(gx * gx + gy * gy)
        mx = mag.max()
        if mx > 0:
            mag = mag / mx * (255.0 if arr.dtype == numpy.uint8 else 1.0)
        return numpy.concatenate(
            [arr, mag[:, :, None].astype(arr.dtype)], axis=2)

    def __call__(self, arr, augment=False):
        """Full pipeline; ``augment`` enables the random crop/mirror
        variants (train class only)."""
        arr = self._scale(arr)
        arr = self._rotate(arr, augment)
        arr = self._crop(arr, augment)
        arr = self._mirror(arr, augment)
        arr = self._sobel(arr)
        if arr.dtype == numpy.uint8:
            arr = arr.astype(numpy.float32) / 255.0
        return numpy.ascontiguousarray(arr, numpy.float32)


class FileImageLoaderBase(object):
    """Directory/glob scanning + label-from-path mixin
    (ref: loader/file_image.py:53).

    ``test_paths`` / ``validation_paths`` / ``train_paths`` are lists of
    directories (scanned recursively for :data:`IMAGE_EXTENSIONS`) or
    explicit file paths.
    """

    def __init__(self, *args, test_paths=(), validation_paths=(),
                 train_paths=(), filename_re=None, **kwargs):
        # keyword-only own args; positionals (workflow) pass through the
        # cooperative chain untouched
        super(FileImageLoaderBase, self).__init__(*args, **kwargs)
        self.class_paths = [list(test_paths), list(validation_paths),
                            list(train_paths)]
        #: optional regex whose first group is the label
        #: (ref: file_loader.py label regex support)
        self.filename_re = re.compile(filename_re) if filename_re else None
        self.class_keys = [[], [], []]

    def scan_files(self):
        warn = getattr(self, "warning", None)
        for ci, paths in enumerate(self.class_paths):
            keys = []
            for p in paths:
                if os.path.isdir(p):
                    for dirpath, _, files in sorted(os.walk(p)):
                        for fn in sorted(files):
                            if fn.lower().endswith(IMAGE_EXTENSIONS):
                                keys.append(os.path.join(dirpath, fn))
                elif os.path.isfile(p):
                    keys.append(p)
            if self.filename_re is not None:
                # drop files the label regex can't classify — a single
                # stray file would otherwise crash label mapping later
                matched = [k for k in keys
                           if self.get_image_label(k) is not None]
                if len(matched) != len(keys) and warn is not None:
                    warn("%d file(s) did not match filename_re and were "
                         "skipped", len(keys) - len(matched))
                keys = matched
            self.class_keys[ci] = keys

    def get_image_label(self, path):
        """Label for one file: regex group if configured, else the parent
        directory name (ref convention)."""
        if self.filename_re is not None:
            m = self.filename_re.search(os.path.basename(path))
            return m.group(1) if m else None
        return os.path.basename(os.path.dirname(path))


class FileImageLoader(FileImageLoaderBase, Loader):
    """Streaming image loader (ref: ImageLoader + FileImageLoaderBase
    composed): decodes each minibatch's files on demand — for corpora
    that don't fit in RAM.  Augmentation (random crop/mirror) applies to
    train-class minibatches only."""

    def __init__(self, workflow, color_space="RGB", scale=None,
                 scale_maintain_aspect_ratio=False, crop=None, mirror=False,
                 rotation=None, add_sobel=False, **kwargs):
        # path kwargs are consumed by the FileImageLoaderBase mixin, the
        # rest by Loader
        super(FileImageLoader, self).__init__(workflow, **kwargs)
        self.pipeline = ImagePipeline(
            color_space=color_space, scale=scale,
            scale_maintain_aspect_ratio=scale_maintain_aspect_ratio,
            crop=crop, mirror=mirror, rotation=rotation,
            add_sobel=add_sobel, prng=self.prng)

    def load_data(self):
        self.scan_files()
        self.class_lengths[:] = [len(k) for k in self.class_keys]
        self._all_keys = sum(self.class_keys, [])
        if not self._all_keys:
            raise ValueError("%s: no image files found" % self)
        # labels come from paths alone — build the mapping here so the
        # analysis pass never decodes pixels just to collect labels
        labels = {self.get_image_label(k) for k in self._all_keys}
        labels.discard(None)
        if labels and not all(
                isinstance(l, (int, numpy.integer)) for l in labels):
            self.labels_mapping = {
                l: i for i, l in enumerate(sorted(labels))}
        # probe one image for the sample shape
        self._sample_shape = self.pipeline(
            self.pipeline.decode(self._all_keys[0])).shape

    def create_minibatch_data(self):
        self.minibatch_data.reset(numpy.zeros(
            (self.max_minibatch_size,) + self._sample_shape,
            numpy.float32))

    def iterate_train(self):
        lo = self.class_end_offsets[VALID]
        hi = self.class_end_offsets[TRAIN]
        step = max(1, self.max_minibatch_size)
        for start in range(lo, hi, step):
            keys = self._all_keys[start:min(start + step, hi)]
            data = numpy.stack([
                self.pipeline(self.pipeline.decode(k)) for k in keys])
            yield data, [self.get_image_label(k) for k in keys]

    def fill_minibatch(self):
        augment = self.minibatch_class == TRAIN
        idx = self.minibatch_indices.mem[:self.minibatch_size]
        for i, sample_idx in enumerate(idx):
            key = self._all_keys[int(sample_idx)]
            self.minibatch_data.mem[i] = self.pipeline(
                self.pipeline.decode(key), augment=augment)
            self.raw_minibatch_labels[i] = self.get_image_label(key)


class FullBatchImageLoader(FullBatchLoader):
    """FullBatch variant fed by in-memory images
    (ref: loader/fullbatch_image.py:56): subclasses provide decoded
    samples via :meth:`load_images`; the pipeline materializes them once
    into ``original_data`` and training runs entirely from HBM."""

    hide_from_registry = True

    def __init__(self, workflow, color_space="RGB", scale=None,
                 scale_maintain_aspect_ratio=False, crop=None, mirror=False,
                 rotation=None, add_sobel=False, **kwargs):
        super(FullBatchImageLoader, self).__init__(workflow, **kwargs)
        self.pipeline = ImagePipeline(
            color_space=color_space, scale=scale,
            scale_maintain_aspect_ratio=scale_maintain_aspect_ratio,
            crop=crop, mirror=mirror, rotation=rotation,
            add_sobel=add_sobel, prng=self.prng)

    def load_images(self):
        """Yield (class_index, image_array, label) triples."""
        raise NotImplementedError()

    def load_data(self):
        per_class = [[], [], []]
        labels_per_class = [[], [], []]
        for ci, arr, label in self.load_images():
            per_class[ci].append(self.pipeline(arr))
            labels_per_class[ci].append(label)
        self.class_lengths[:] = [len(c) for c in per_class]
        samples = sum(per_class, [])
        if not samples:
            raise ValueError("%s: load_images produced nothing" % self)
        self.original_data = numpy.stack(samples)
        labels = sum(labels_per_class, [])
        if any(l is not None for l in labels):
            # original_labels stays RAW — fullbatch._post_load applies
            # labels_mapping (pre-mapping would double-map to -1)
            if not all(isinstance(l, (int, numpy.integer)) for l in labels):
                self.labels_mapping = {
                    l: i for i, l in enumerate(sorted(set(labels)))}
            self.original_labels = list(labels)


class FullBatchFileImageLoader(FileImageLoaderBase, FullBatchImageLoader):
    """Directory-scanning FullBatch image loader (the reference's most
    used image entry point: FullBatchAutoLabelFileImageLoader)."""

    def load_images(self):
        self.scan_files()
        for ci, keys in enumerate(self.class_keys):
            for k in keys:
                yield ci, self.pipeline.decode(k), self.get_image_label(k)


class FullBatchImageLoaderMSE(FullBatchLoaderMSE, FullBatchImageLoader):
    """MSE (target-image) variant (ref: fullbatch_image.py:179-268 +
    image_mse.py): :meth:`load_images` additionally yields the target
    image; targets flow through the same pipeline."""

    def load_images(self):
        """Yield (class_index, image_array, target_array)."""
        raise NotImplementedError()

    def load_data(self):
        per_class, targets_per_class = [[], [], []], [[], [], []]
        for ci, arr, target in self.load_images():
            per_class[ci].append(self.pipeline(arr))
            targets_per_class[ci].append(self.pipeline(target))
        self.class_lengths[:] = [len(c) for c in per_class]
        samples = sum(per_class, [])
        if not samples:
            raise ValueError("%s: load_images produced nothing" % self)
        self.original_data = numpy.stack(samples)
        self.original_targets = numpy.stack(sum(targets_per_class, []))
