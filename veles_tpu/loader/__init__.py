"""Data loading stack (rebuild of veles/loader/, 5.1 kLoC, 17 modules).

- :mod:`veles_tpu.loader.base`       — Loader: minibatch serving, class
  split, shuffling, epoch flags, failed-minibatch requeue
- :mod:`veles_tpu.loader.fullbatch`  — device-resident dataset + traced
  gather (the TPU path for datasets that fit in HBM)
- :mod:`veles_tpu.loader.pickles`    — datasets from pickle files
- :mod:`veles_tpu.loader.image`      — directory/file image datasets (PIL)
- :mod:`veles_tpu.loader.saver`      — minibatch stream save / replay
- :mod:`veles_tpu.loader.interactive`— feed minibatches from code
- :mod:`veles_tpu.loader.restful`    — feed minibatches from HTTP (serving)
"""

from veles_tpu.loader.base import (  # noqa: F401
    CLASS_NAME, TEST, TRAIN, VALID, ILoader, Loader)
from veles_tpu.loader.fullbatch import (  # noqa: F401
    FullBatchLoader, FullBatchLoaderMSE)
