"""Ensemble (stacking) loader — rebuild of veles/loader/ensemble.py:
53-143: the meta-model's dataset is the concatenated per-instance
outputs of a trained ensemble over a base dataset.

The reference read per-model output dumps; here each instance's
snapshot (from the ensemble summary JSON) is loaded and its forward
chain applied to the base loader's samples — same capability, one file
format fewer."""

import json

import numpy

from veles_tpu.loader.fullbatch import FullBatchLoader


class EnsembleLoader(FullBatchLoader):
    """features[i] = concat(model_k.forward(sample_i) for k) over the
    ensemble's instances; labels = the base loader's labels."""

    def __init__(self, workflow, summary_path=None, base_loader=None,
                 batch=256, **kwargs):
        super(EnsembleLoader, self).__init__(workflow, **kwargs)
        if summary_path is None or base_loader is None:
            raise ValueError("summary_path and base_loader are required")
        self.summary_path = summary_path
        #: an (uninitialized) loader supplying the underlying dataset
        self.base_loader = base_loader
        self.batch = batch

    def _forward_outputs(self, workflow, data):
        """Apply a snapshot workflow's forward chain on host-visible
        data in minibatch chunks."""
        import jax.numpy as jnp
        outs = []
        for start in range(0, len(data), self.batch):
            h = jnp.asarray(data[start:start + self.batch])
            for u in workflow.forwards:
                params = {k: jnp.asarray(a.map_read().mem)
                          for k, a in u.param_arrays().items()}
                h = u.apply(params, h)
            outs.append(numpy.asarray(h))
        return numpy.concatenate(outs)

    def load_data(self):
        from veles_tpu.snapshotter import SnapshotterToFile
        with open(self.summary_path) as f:
            summary = json.load(f)
        base = self.base_loader
        base.load_data()
        data = numpy.asarray(base.original_data, numpy.float32)
        features = []
        for inst in summary["instances"]:
            snap = inst.get("snapshot")
            if not snap:
                continue
            wf = SnapshotterToFile.import_file(snap)
            features.append(self._forward_outputs(wf, data))
        if not features:
            raise ValueError("no usable snapshots in %s"
                             % self.summary_path)
        self.class_lengths[:] = list(base.class_lengths)
        self.original_data = numpy.concatenate(features, axis=1)
        self.original_labels = base.original_labels
