"""HDF5 loaders (rebuild of veles/loader/loader_hdf5.py:48-151).

File layout matches the reference's convention: one HDF5 file per class
(test/validation/train) with ``data`` [n, ...] and ``labels`` [n]
datasets.  :class:`FullBatchHDF5Loader` materializes everything into
the HBM-resident dataset; :class:`HDF5Loader` streams minibatches from
the on-disk datasets (bigger-than-RAM corpora).
"""

import numpy

from veles_tpu.loader.base import TRAIN, VALID, Loader
from veles_tpu.loader.fullbatch import FullBatchLoader

try:
    import h5py
    HAS_H5PY = True
except ImportError:  # pragma: no cover
    HAS_H5PY = False


def _require_h5py():
    if not HAS_H5PY:  # pragma: no cover
        raise RuntimeError("h5py is unavailable")


class FullBatchHDF5Loader(FullBatchLoader):
    """All class files into memory → HBM (ref: loader_hdf5.py:48)."""

    def __init__(self, workflow, test_path=None, validation_path=None,
                 train_path=None, data_name="data", labels_name="labels",
                 **kwargs):
        super(FullBatchHDF5Loader, self).__init__(workflow, **kwargs)
        self.class_files = [test_path, validation_path, train_path]
        self.data_name = data_name
        self.labels_name = labels_name

    def load_data(self):
        _require_h5py()
        datas, labels, labelled = [], [], []
        for ci, path in enumerate(self.class_files):
            if not path:
                self.class_lengths[ci] = 0
                continue
            with h5py.File(path, "r") as f:
                d = numpy.asarray(f[self.data_name])
                datas.append(d)
                self.class_lengths[ci] = len(d)
                has = self.labels_name in f
                labelled.append(has)
                if has:
                    labels.extend(numpy.asarray(f[self.labels_name])
                                  .tolist())
        if not datas:
            raise ValueError("%s: no HDF5 files given" % self)
        if labels and not all(labelled):
            # partial labels would silently shift every row's label
            raise ValueError(
                "%s: %r present in some class files but not all"
                % (self, self.labels_name))
        self.original_data = numpy.concatenate(datas).astype(
            numpy.float32)
        if labels:
            self.original_labels = labels


class HDF5Loader(Loader):
    """Streaming variant: minibatches gathered straight from the h5py
    datasets (lazy chunked reads)."""

    def __init__(self, workflow, test_path=None, validation_path=None,
                 train_path=None, data_name="data", labels_name="labels",
                 **kwargs):
        super(HDF5Loader, self).__init__(workflow, **kwargs)
        self.class_files = [test_path, validation_path, train_path]
        self.data_name = data_name
        self.labels_name = labels_name

    def init_unpickled(self):
        super(HDF5Loader, self).init_unpickled()
        self._files_ = None
        self._datasets_ = None
        self._labels_ = None

    def _open(self):
        _require_h5py()
        if self._files_ is not None:
            return
        self._files_, self._datasets_, self._labels_ = [], [], []
        for path in self.class_files:
            if not path:
                self._files_.append(None)
                self._datasets_.append(None)
                self._labels_.append(None)
                continue
            f = h5py.File(path, "r")
            self._files_.append(f)
            self._datasets_.append(f[self.data_name])
            self._labels_.append(f.get(self.labels_name))
        return

    def load_data(self):
        self._open()
        for ci, ds in enumerate(self._datasets_):
            self.class_lengths[ci] = 0 if ds is None else len(ds)

    def create_minibatch_data(self):
        self._open()
        shape = next(ds.shape[1:] for ds in self._datasets_
                     if ds is not None)
        self.minibatch_data.reset(numpy.zeros(
            (self.max_minibatch_size,) + shape, numpy.float32))

    def iterate_train(self):
        self._open()
        ds = self._datasets_[TRAIN]
        if ds is None:
            return
        lab = self._labels_[TRAIN]
        step = max(1, self.max_minibatch_size)
        for start in range(0, len(ds), step):
            stop = min(start + step, len(ds))
            labels = None if lab is None \
                else numpy.asarray(lab[start:stop]).tolist()
            yield numpy.asarray(ds[start:stop]), labels

    def _locate(self, global_idx):
        """global sample index → (class index, local index)."""
        base = 0
        for ci, n in enumerate(self.class_lengths):
            if global_idx < base + n:
                return ci, global_idx - base
            base += n
        raise IndexError(global_idx)

    def fill_minibatch(self):
        self._open()
        for i, gidx in enumerate(
                self.minibatch_indices.mem[:self.minibatch_size]):
            ci, local = self._locate(int(gidx))
            self.minibatch_data.mem[i] = self._datasets_[ci][local]
            lab = self._labels_[ci]
            self.raw_minibatch_labels[i] = \
                None if lab is None else lab[local].item()

    def __del__(self):
        for f in (self._files_ or []):
            if f is not None:
                try:
                    f.close()
                except Exception:
                    pass
