"""Sound loaders (rebuild of veles/loader/libsndfile.py:42-133 +
libsndfile_loader.py:46-107 + the GTZAN pipeline entry).

Decoding: libsndfile via ctypes when present (the reference's path),
else the stdlib ``wave``/``aifc``-free fallback through
``scipy.io.wavfile`` — this image ships scipy but not libsndfile.
Decoded audio is float32 in [-1, 1], [n] mono or [n, channels].
"""

import ctypes
import ctypes.util
import os

import numpy

from veles_tpu.loader.fullbatch import FullBatchLoader

SOUND_EXTENSIONS = (".wav", ".flac", ".ogg", ".aiff", ".au")


def _decode_scipy(path):
    from scipy.io import wavfile
    rate, data = wavfile.read(path)
    if data.dtype.kind == "i":
        data = data.astype(numpy.float32) / numpy.iinfo(data.dtype).max
    elif data.dtype.kind == "u":
        info = numpy.iinfo(data.dtype)
        data = (data.astype(numpy.float32) - info.max / 2) / (info.max / 2)
    else:
        data = data.astype(numpy.float32)
    return data, rate


class _Libsndfile:
    """Minimal ctypes binding (ref: veles/loader/libsndfile.py:42)."""

    class SF_INFO(ctypes.Structure):
        _fields_ = [("frames", ctypes.c_int64),
                    ("samplerate", ctypes.c_int),
                    ("channels", ctypes.c_int),
                    ("format", ctypes.c_int),
                    ("sections", ctypes.c_int),
                    ("seekable", ctypes.c_int)]

    def __init__(self):
        name = ctypes.util.find_library("sndfile")
        if not name:
            raise OSError("libsndfile not found")
        lib = ctypes.CDLL(name)
        lib.sf_open.restype = ctypes.c_void_p
        lib.sf_open.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                ctypes.POINTER(self.SF_INFO)]
        lib.sf_readf_float.restype = ctypes.c_int64
        lib.sf_readf_float.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_float),
            ctypes.c_int64]
        lib.sf_close.argtypes = [ctypes.c_void_p]
        self.lib = lib

    def decode(self, path):
        info = self.SF_INFO()
        handle = self.lib.sf_open(path.encode(), 0x10, info)  # SFM_READ
        if not handle:
            raise OSError("libsndfile cannot open %s" % path)
        try:
            buf = numpy.zeros(info.frames * info.channels, numpy.float32)
            got = self.lib.sf_readf_float(
                handle,
                buf.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                info.frames)
            data = buf[:got * info.channels]
            if info.channels > 1:
                data = data.reshape(-1, info.channels)
            return data, info.samplerate
        finally:
            self.lib.sf_close(handle)


_sndfile = None


def decode_sound(path):
    """File → (float32 samples, sample_rate)."""
    global _sndfile
    if _sndfile is None:
        try:
            _sndfile = _Libsndfile()
        except OSError:
            _sndfile = False
    if _sndfile:
        try:
            return _sndfile.decode(path)
        except OSError:
            pass
    return _decode_scipy(path)


class SoundLoader(FullBatchLoader):
    """Directory-scanning audio loader: label = parent directory (the
    GTZAN corpus layout, genres/<genre>/<track>.wav), samples = feature
    vectors from a :mod:`veles_tpu.snd_features` XML pipeline
    (ref: veles/loader/libsndfile_loader.py + genre_recognition.xml)."""

    def __init__(self, workflow, features_xml=None, train_paths=(),
                 validation_paths=(), test_paths=(), max_seconds=None,
                 **kwargs):
        super(SoundLoader, self).__init__(workflow, **kwargs)
        self.features_xml = features_xml
        self.class_paths = [list(test_paths), list(validation_paths),
                            list(train_paths)]
        self.max_seconds = max_seconds
        self._tree = None

    def scan(self):
        keys = [[], [], []]
        for ci, paths in enumerate(self.class_paths):
            for p in paths:
                if os.path.isdir(p):
                    for dirpath, _, files in sorted(os.walk(p)):
                        for fn in sorted(files):
                            if fn.lower().endswith(SOUND_EXTENSIONS):
                                keys[ci].append(
                                    os.path.join(dirpath, fn))
                elif os.path.isfile(p):
                    keys[ci].append(p)
        return keys

    def features_of(self, path):
        from veles_tpu.snd_features import (
            FeatureExtractor, parse_features_xml)
        data, rate = decode_sound(path)
        if self.max_seconds:
            data = data[:int(self.max_seconds * rate)]
        if self._tree is None:
            self._tree = parse_features_xml(self.features_xml)
        feats = FeatureExtractor(self._tree, rate).extract(data)
        return numpy.concatenate([feats[k] for k in sorted(feats)])

    def load_data(self):
        keys = self.scan()
        samples, labels = [], []
        lengths = []
        for ci in (0, 1, 2):
            for path in keys[ci]:
                samples.append(self.features_of(path))
                labels.append(os.path.basename(os.path.dirname(path)))
            lengths.append(len(keys[ci]))
        if not samples:
            raise ValueError("%s: no sound files found" % self)
        # tracks of unequal length produce unequal Stats rows: pad to
        # the longest vector (zero-padded tail, the reference padded
        # feature streams the same way)
        width = max(len(s) for s in samples)
        data = numpy.zeros((len(samples), width), numpy.float32)
        for i, s in enumerate(samples):
            data[i, :len(s)] = s
        self.class_lengths[:] = lengths
        self.original_data = data
        mapping = {l: i for i, l in enumerate(sorted(set(labels)))}
        self.labels_mapping = mapping
        # original_labels carries the RAW directory names — fullbatch's
        # _post_load maps them through labels_mapping (pre-mapping here
        # would double-map every label to the -1 sentinel)
        self.original_labels = list(labels)
