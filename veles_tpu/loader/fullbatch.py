"""FullBatchLoader — whole dataset resident in device HBM.

Rebuild of veles/loader/fullbatch.py:79-566.  The reference uploaded the
dataset to GPU memory and gathered minibatches with a dedicated kernel
(ocl/fullbatch_loader.cl / cuda/fullbatch_loader.cu) with CPU fallback on
OOM.  TPU-native: the dataset is one ``jax.Array`` in HBM, the minibatch
gather is a jitted ``jnp.take`` (XLA emits the dynamic-gather), and the
normalizer runs once over the whole dataset at upload time instead of
per-minibatch.  Falls back to host-side numpy gather when the dataset
exceeds the HBM budget.
"""

import jax
import jax.numpy as jnp
import numpy

from veles_tpu.loader.base import (
    INDEX_DTYPE, LABEL_DTYPE, TRAIN, VALID, Loader)
from veles_tpu.memory import Array


class FullBatchLoader(Loader):
    """Device-resident dataset loader (ref: loader/fullbatch.py:79).

    Subclasses implement :meth:`load_data` filling ``original_data``
    (numpy [total, ...]) + optionally ``original_labels`` (list/array of
    labels, one per sample) and ``class_lengths``.
    """

    hide_from_registry = True

    #: fraction of free device memory the dataset may occupy before
    #: falling back to host gather (ref OOM fallback: fullbatch.py:158-242)
    DEVICE_MEMORY_FRACTION = 0.8

    def __init__(self, workflow, force_numpy=False, **kwargs):
        super(FullBatchLoader, self).__init__(workflow, **kwargs)
        self.original_data = None
        self.original_labels = None
        self.force_numpy = force_numpy
        self.device = None

    supports_span = True

    def init_unpickled(self):
        super(FullBatchLoader, self).init_unpickled()
        self._dataset_dev_ = None
        self._labels_dev_ = None
        self._gather_jit_ = None

    @property
    def span_capable(self):
        # the trainer gathers targets in-graph, so a device-resident
        # label (or MSE target) array is required
        return super(FullBatchLoader, self).span_capable \
            and self._dataset_dev_ is not None \
            and (self._labels_dev_ is not None
                 or getattr(self, "_targets_dev_", None) is not None)

    @property
    def dataset_dev(self):
        """The HBM-resident dataset (trainer scans gather from it)."""
        return self._dataset_dev_

    @property
    def labels_dev(self):
        return self._labels_dev_

    def rehome_dataset(self, sharding):
        """Re-place the resident dataset (e.g. replicate over a mesh);
        the previous placement is released.  Multi-host meshes assemble
        from host data (parallel.sharding.put)."""
        from veles_tpu.parallel.sharding import put
        self._dataset_dev_ = put(self._dataset_dev_, sharding)
        if self._labels_dev_ is not None:
            self._labels_dev_ = put(self._labels_dev_, sharding)

    # -- ILoader ---------------------------------------------------------------

    def create_minibatch_data(self):
        shape = (self.max_minibatch_size,) + self.original_data.shape[1:]
        self.minibatch_data.reset(
            numpy.zeros(shape, self.original_data.dtype))

    def iterate_train(self):
        lo = self.class_end_offsets[VALID]
        hi = self.class_end_offsets[TRAIN]
        step = max(1, self.max_minibatch_size)
        for start in range(lo, hi, step):
            stop = min(start + step, hi)
            labels = None
            if self.original_labels is not None:
                labels = list(self.original_labels[start:stop])
            yield self.original_data[start:stop], labels

    # -- lifecycle -------------------------------------------------------------

    def initialize(self, device=None, **kwargs):
        if device is not None:
            self.device = device
        super(FullBatchLoader, self).initialize(**kwargs)
        self._post_load()

    def _post_load(self):
        from veles_tpu.normalization import NoneNormalizer
        # normalize the whole dataset once (device path applies it here
        # rather than per minibatch); an inference-only loader whose
        # normalizer state was transferred from training still normalizes
        if isinstance(self.original_data, jax.Array):
            # device-synthesized dataset (e.g. the bench loaders): keep
            # it in HBM — normalizers are host-side, so only the
            # identity normalizer avoids a device→host→device round-trip
            if not isinstance(self.normalizer, NoneNormalizer):
                self.original_data = numpy.ascontiguousarray(
                    self.normalizer.normalize(
                        numpy.asarray(self.original_data)))
        elif self.normalizer.is_initialized:
            self.original_data = numpy.ascontiguousarray(
                self.normalizer.normalize(self.original_data))
        self._numeric_labels = None
        if self.original_labels is not None:
            if self.labels_mapping:
                self._numeric_labels = numpy.array(
                    [self.labels_mapping.get(l, -1)
                     for l in self.original_labels], LABEL_DTYPE)
            else:
                self._numeric_labels = numpy.asarray(
                    self.original_labels, LABEL_DTYPE)
        self._maybe_upload()

    def _maybe_upload(self):
        if self.force_numpy or self.device is None:
            return
        nbytes = self.original_data.nbytes
        stats = self.device.memory_stats()
        limit = stats.get("bytes_limit")
        if limit and nbytes > self.DEVICE_MEMORY_FRACTION * limit:
            self.warning(
                "dataset (%.1f MiB) exceeds device budget — host gather",
                nbytes / 2**20)
            return
        self._dataset_dev_ = jax.device_put(
            self.original_data, self.device.jax_device)
        if self._numeric_labels is not None:
            self._labels_dev_ = jax.device_put(
                self._numeric_labels, self.device.jax_device)

        # computation follows the dataset's committed placement; padded
        # tail rows are zeroed in-kernel (size is traced, shapes static)
        def gather(ds, idx, size):
            rows = jnp.take(ds, idx, axis=0, mode="clip")
            mask = jnp.arange(rows.shape[0]) < size
            return jnp.where(
                mask.reshape((-1,) + (1,) * (rows.ndim - 1)), rows, 0)

        from veles_tpu.telemetry import track_jit
        self._gather_jit_ = track_jit("loader.gather", jax.jit(gather))

    # -- serving ---------------------------------------------------------------

    def fill_minibatch(self):
        size = self.minibatch_size
        idx = self.minibatch_indices.mem[:size]
        if self._dataset_dev_ is not None:
            full_idx = numpy.zeros(self.max_minibatch_size, INDEX_DTYPE)
            full_idx[:size] = idx
            self.minibatch_data.devmem = self._gather_jit_(
                self._dataset_dev_, jnp.asarray(full_idx),
                numpy.int32(size))
        else:
            self.minibatch_data.mem[:size] = self.original_data[idx]
        if self._numeric_labels is not None:
            self.minibatch_labels.mem[:size] = self._numeric_labels[idx]

    def _normalize_minibatch(self):
        pass  # already normalized at upload

    def _map_minibatch_labels(self):
        pass  # numeric labels gathered directly

    def _pad_tail(self, size):
        if self._dataset_dev_ is not None:
            # data rows already zero-masked in the gather kernel
            self.minibatch_labels.mem[size:] = -1
            self.minibatch_indices.mem[size:] = -1
        else:
            super(FullBatchLoader, self)._pad_tail(size)

    def __getstate__(self):
        state = super(FullBatchLoader, self).__getstate__()
        # the dataset is reloadable via load_data(); keep snapshots small
        # (ref: fullbatch.py stored datasets out-of-line similarly)
        state.pop("original_data", None)
        state.pop("original_labels", None)
        state.pop("_numeric_labels", None)
        return state


class FullBatchLoaderMSE(FullBatchLoader):
    """Adds regression targets (ref: fullbatch.py MSE variants):
    ``original_targets`` [total, ...] gathered into
    ``minibatch_targets``."""

    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        super(FullBatchLoaderMSE, self).__init__(workflow, **kwargs)
        self.original_targets = None
        self.minibatch_targets = Array()

    def init_unpickled(self):
        super(FullBatchLoaderMSE, self).init_unpickled()
        self._targets_dev_ = None

    @property
    def targets_dev(self):
        return self._targets_dev_

    def rehome_dataset(self, sharding):
        super(FullBatchLoaderMSE, self).rehome_dataset(sharding)
        if self._targets_dev_ is not None:
            from veles_tpu.parallel.sharding import put
            self._targets_dev_ = put(self._targets_dev_, sharding)

    def create_minibatch_data(self):
        super(FullBatchLoaderMSE, self).create_minibatch_data()
        shape = (self.max_minibatch_size,) + self.original_targets.shape[1:]
        self.minibatch_targets.reset(
            numpy.zeros(shape, self.original_targets.dtype))

    def _maybe_upload(self):
        super(FullBatchLoaderMSE, self)._maybe_upload()
        if self._dataset_dev_ is not None:
            self._targets_dev_ = jax.device_put(
                self.original_targets, self.device.jax_device)

    def fill_minibatch(self):
        super(FullBatchLoaderMSE, self).fill_minibatch()
        size = self.minibatch_size
        idx = self.minibatch_indices.mem[:size]
        if self._targets_dev_ is not None:
            full_idx = numpy.zeros(self.max_minibatch_size, INDEX_DTYPE)
            full_idx[:size] = idx
            self.minibatch_targets.devmem = self._gather_jit_(
                self._targets_dev_, jnp.asarray(full_idx),
                numpy.int32(size))
        else:
            self.minibatch_targets.mem[:size] = self.original_targets[idx]

    def __getstate__(self):
        state = super(FullBatchLoaderMSE, self).__getstate__()
        state.pop("original_targets", None)
        return state
