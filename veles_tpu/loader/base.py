"""Loader — the minibatch-serving unit.

Rebuild of veles/loader/base.py:100-1181.  Serves minibatches from three
sample classes (test / validation / train, ref: base.py:80), walking the
concatenated index space ``[test | validation | train]`` each epoch,
shuffling the train span between epochs, zero-padding the tail minibatch
to ``max_minibatch_size`` (which doubles as the jit static-shape
guarantee on TPU — every minibatch the compiled program sees has the
same shape, ref tail-pad: base.py:749-753).

Distributed behavior (the elastic DCN job-queue layer, SURVEY.md §2.3):
the coordinator serves *index ranges* to workers
(``generate_data_for_slave``), requeues ranges from dropped workers
(``failed_minibatches``, ref: base.py:679-687), and workers fill data
locally from their own dataset copy.
"""

import time

import numpy

from veles_tpu import prng as prng_mod
from veles_tpu.distributable import IDistributable
from veles_tpu.memory import Array
from veles_tpu.mutable import Bool
from veles_tpu.normalization import get_normalizer
from veles_tpu.units import Unit
from veles_tpu.result_provider import IResultProvider

TEST, VALID, TRAIN = 0, 1, 2
CLASS_NAME = ("test", "validation", "train")

INDEX_DTYPE = numpy.int32
LABEL_DTYPE = numpy.int32


class ILoader:
    """The subclass contract (ref: base.py:100-120)."""

    def load_data(self):
        """Discover the dataset: set ``class_lengths`` and load/locate
        sample storage."""
        raise NotImplementedError()

    def create_minibatch_data(self):
        """Allocate ``minibatch_data`` (shape [max_minibatch_size, ...])."""
        raise NotImplementedError()

    def fill_minibatch(self):
        """Copy rows ``minibatch_indices[:minibatch_size]`` of the dataset
        into minibatch_data/labels."""
        raise NotImplementedError()


class Loader(Unit, ILoader, IDistributable, IResultProvider):
    """Minibatch server (ref: veles/loader/base.py:120)."""

    hide_from_registry = True
    VIEW_GROUP = "LOADER"
    negotiates_on_connect = True

    #: loaders whose serving cannot be produced ahead of the waves
    #: (queue-fed interactive streams) opt out of the asynchronous
    #: input pipeline here
    prefetchable = True

    def __init__(self, workflow, minibatch_size=100, shuffle_limit=None,
                 train_ratio=1.0, normalization_type="none",
                 normalization_parameters=None, prng_key="loader",
                 prefetch=None, **kwargs):
        super(Loader, self).__init__(workflow, **kwargs)
        self.max_minibatch_size = minibatch_size
        #: asynchronous input pipeline override: None follows
        #: ``root.common.loader.prefetch``; 0/False pins the
        #: synchronous path; an int is the prefetch depth
        self.prefetch = prefetch
        #: how many times shuffle() may still permute the train span
        #: (None = unlimited; 0 = deterministic order, ref base.py)
        self.shuffle_limit = shuffle_limit
        self.train_ratio = train_ratio
        self.prng = prng_mod.get(prng_key)

        self.class_lengths = [0, 0, 0]
        self.class_end_offsets = [0, 0, 0]

        self.minibatch_class = TRAIN
        self.minibatch_size = 0
        self.minibatch_offset = 0
        self.minibatch_data = Array()
        self.minibatch_labels = Array()
        self.minibatch_indices = Array()
        self.raw_minibatch_labels = []
        self.labels_mapping = {}

        self.shuffled_indices = Array()
        self.global_offset = 0
        self.epoch_number = 0
        self.samples_served = 0
        self.last_minibatch = Bool(False, "last_minibatch")
        self.epoch_ended = Bool(False, "epoch_ended")
        self.train_ended = Bool(False, "train_ended")
        self.failed_minibatches = []

        self.normalization_type = normalization_type
        self.normalization_parameters = normalization_parameters or {}
        self._normalizer = None

    def init_unpickled(self):
        super(Loader, self).init_unpickled()
        #: worker-id -> list of in-flight (offset, size) jobs — volatile,
        #: a restart abandons in-flight bookkeeping (ref: base.py:205)
        self.pending_minibatches_ = {}
        #: span-serving handoff (see :meth:`_serve_span`): index schedule
        #: of the last served class span + freshness flag for the trainer
        self.span_indices_ = None
        self.span_sizes_ = None
        self.span_class_ = None
        self.span_fresh_ = False
        #: the asynchronous input pipeline (loader/prefetch.py):
        #: None = undecided (created lazily on the first streaming
        #: run()), False = decided off, else the live PrefetchPipeline
        self.prefetch_ = None
        self._input_wait_ = None

    # -- derived quantities ---------------------------------------------------

    @property
    def total_samples(self):
        return sum(self.class_lengths)

    @property
    def effective_total_samples(self):
        """train_ratio < 1 trims the train span (ref: base.py:391)."""
        return self.total_samples - int(
            (1.0 - self.train_ratio) * self.class_lengths[TRAIN])

    @property
    def has_labels(self):
        return bool(self.labels_mapping) or any(
            l is not None for l in self.raw_minibatch_labels)

    @property
    def normalizer(self):
        if self._normalizer is None:
            self._normalizer = get_normalizer(
                self.normalization_type, **self.normalization_parameters)
        return self._normalizer

    @property
    def class_ended(self):
        return self.global_offset in self.class_end_offsets \
            or self.global_offset == self.effective_total_samples

    # -- lifecycle ------------------------------------------------------------

    def initialize(self, **kwargs):
        super(Loader, self).initialize(**kwargs)
        from veles_tpu.config import root
        tr = root.common.get("ensemble_train_ratio")
        if tr is not None:
            # ensemble members train on sub-sampled train spans
            # (ref: ensemble/base_workflow.py train_ratio contract)
            self.train_ratio = float(tr)
        self.load_data()
        if self.total_samples == 0:
            raise ValueError("%s: load_data() produced no samples" % self)
        self._calc_class_end_offsets()
        self.info("samples: test %d, validation %d, train %d",
                  *self.class_lengths)
        self.minibatch_indices.reset(
            numpy.zeros(self.max_minibatch_size, INDEX_DTYPE))
        self.minibatch_labels.reset(
            numpy.zeros(self.max_minibatch_size, LABEL_DTYPE))
        self.raw_minibatch_labels = [None] * self.max_minibatch_size
        self.create_minibatch_data()
        if not self.minibatch_data:
            raise ValueError(
                "%s: create_minibatch_data() must allocate minibatch_data"
                % self)
        self._analyze_dataset()
        if not self.shuffled_indices:
            self.shuffled_indices.mem = numpy.arange(
                self.total_samples, dtype=INDEX_DTYPE)
            self.shuffle()

    def _calc_class_end_offsets(self):
        total = 0
        for i, n in enumerate(self.class_lengths):
            total += int(n)
            self.class_end_offsets[i] = total

    def _analyze_dataset(self):
        """One pass over the train set accumulating normalizer stats and
        the label mapping (ref: base.py analyze_dataset, simplified: the
        subclass exposes train data via iterate_train())."""
        from veles_tpu.normalization import StatelessNormalizer
        need_stats = not isinstance(self.normalizer, StatelessNormalizer) \
            and not self.normalizer.is_initialized
        need_labels = not self.labels_mapping
        if not (need_stats or need_labels):
            return
        labels = set()
        for data, batch_labels in self.iterate_train():
            if need_stats:
                self.normalizer.analyze(data)
            if need_labels and batch_labels is not None:
                labels.update(batch_labels)
        if need_labels and labels:
            self.labels_mapping = {
                l: i for i, l in enumerate(sorted(labels))}

    def iterate_train(self):
        """Yield (data, labels) batches of the train set for analysis.
        Subclasses with device-resident data override."""
        return iter(())

    # -- shuffling ------------------------------------------------------------

    def shuffle(self):
        """Permute the train span of shuffled_indices
        (ref: base.py:711)."""
        if self.class_lengths[TRAIN] == 0:
            return
        if self.shuffle_limit is not None:
            if self.shuffle_limit <= 0:
                return
            self.shuffle_limit -= 1
        self.shuffled_indices.map_write()
        self.prng.shuffle(
            self.shuffled_indices.mem[self.class_end_offsets[VALID]:])

    # -- serving (ref: base.py:726-910) ---------------------------------------

    #: subclasses that can hand a whole class span to the trainer in one
    #: device dispatch set this True (see FullBatchLoader)
    supports_span = False
    #: None = auto (the trainer turns it on when it can consume spans);
    #: builders wiring per-minibatch consumers of minibatch_data/labels
    #: into the wave graph must set it to False explicitly
    span_serving = None

    @property
    def span_capable(self):
        """Span serving is a standalone-mode fast path: distributed jobs
        and failed-minibatch refiles stay per-minibatch."""
        return (self.supports_span and bool(self.span_serving)
                and not self.is_master and not self.is_slave
                and not self.failed_minibatches)

    def run(self):
        self.pending_minibatches_.pop(None, None)
        if self.span_capable:
            self._serve_span()
            return
        pipeline = self._ensure_prefetch()
        t0 = time.perf_counter()
        if pipeline is not None:
            pipeline.pop_into(self)
            mode = "prefetch"
        else:
            self.serve_next_minibatch(None)
            self._on_successful_serve()
            mode = "sync"
        self._observe_input_wait(time.perf_counter() - t0, mode)

    # -- asynchronous input pipeline (loader/prefetch.py) ----------------------

    def _prefetch_depth(self):
        """The effective prefetch depth for THIS loader: the
        constructor override wins; otherwise
        ``root.common.loader.prefetch`` {enabled, depth}.  <= 0 means
        the synchronous path."""
        if self.prefetch is not None:
            return int(self.prefetch)
        from veles_tpu.config import root
        cfg = root.common.loader.get_dict(
            "prefetch", {"enabled": True, "depth": 2})
        if not cfg.get("enabled", True):
            return 0
        return int(cfg.get("depth", 2))

    def _ensure_prefetch(self):
        """Lazily decide/create the prefetch pipeline.  Falls back to
        the synchronous path (returns None) for anything the
        ahead-of-wave production cannot replay exactly: distributed
        master/slave serving, cross-process meshes, refiled
        minibatches — and for loaders that opted out."""
        if self.prefetch_ is False:
            return None
        if self.prefetch_ is not None:
            return self.prefetch_
        depth = self._prefetch_depth()
        enabled = (depth > 0 and self.prefetchable
                   and self.is_standalone
                   and not self.failed_minibatches)
        if enabled:
            import jax
            enabled = jax.process_count() == 1
        if not enabled:
            self.prefetch_ = False
            return None
        from veles_tpu.loader.prefetch import PrefetchPipeline
        self.prefetch_ = PrefetchPipeline(self, depth)
        self.debug("asynchronous input pipeline on (depth %d)", depth)
        return self.prefetch_

    def _observe_input_wait(self, dt, mode):
        """veles_input_wait_seconds: how long THIS wave blocked on
        input before the trainer could dispatch — the decode+upload
        cost on the sync path, the pop wait on the prefetch path."""
        import veles_tpu.telemetry as telemetry
        if not telemetry.enabled():
            return
        if self._input_wait_ is None or self._input_wait_[0] != mode:
            hist = telemetry.metrics.histogram(
                "veles_input_wait_seconds",
                "time the trainer actually blocked on input per "
                "minibatch wave (sync: decode+normalize+upload; "
                "prefetch: ready-queue wait)", ("loader", "mode"))
            self._input_wait_ = (mode, hist.labels(self.name, mode))
        self._input_wait_[1].observe(dt)

    def stop(self):
        pipeline = self.prefetch_
        if pipeline not in (None, False):
            pipeline.close()
            self.prefetch_ = None
        super(Loader, self).stop()

    def _serve_span(self):
        """Serve ALL remaining minibatches of the current class span at
        once: publish the index schedule (``span_indices_`` [K, mb] +
        ``span_sizes_`` [K]) for the trainer to scan over in one jitted
        dispatch, and advance the host bookkeeping to the span end.  The
        flag sequence the Decision unit observes is identical to the
        per-minibatch path's boundary waves (one wave per class span
        instead of one per minibatch)."""
        if self.global_offset >= self.effective_total_samples:
            self.global_offset = 0
            self.shuffle()
        ci, _ = self._class_by_offset(self.global_offset)
        span_end = self._effective_end_offsets()[ci]
        start = self.global_offset
        span = span_end - start
        mb = self.max_minibatch_size
        k = -(-span // mb)
        self.shuffled_indices.map_read()
        idx = numpy.full((k * mb,), -1, INDEX_DTYPE)
        idx[:span] = self.shuffled_indices.mem[start:span_end]
        self.span_indices_ = idx.reshape(k, mb)
        sizes = numpy.full((k,), mb, INDEX_DTYPE)
        sizes[-1] = span - (k - 1) * mb
        self.span_sizes_ = sizes
        self.span_class_ = ci
        self.span_fresh_ = True

        self.minibatch_class = ci
        self.minibatch_offset = span_end
        self.minibatch_size = int(sizes[-1])
        self.global_offset = span_end
        self.train_ended.set(
            self.global_offset >= self.effective_total_samples)
        self.samples_served += span
        if self.effective_total_samples:
            self.epoch_number = \
                self.samples_served // self.effective_total_samples
        self._update_flags()

    def serve_next_minibatch(self, slave_id):
        try:
            minibatch_def = self.failed_minibatches.pop()
        except IndexError:
            minibatch_def = self._advance_global_offset()
        offset, size = minibatch_def
        self.pending_minibatches_.setdefault(slave_id, []).append(
            minibatch_def)
        self.minibatch_offset, self.minibatch_size = offset, size

        self.minibatch_data.map_invalidate()
        self.minibatch_labels.map_invalidate()
        self.minibatch_indices.map_invalidate()
        self.shuffled_indices.map_read()
        self.minibatch_indices.mem[:size] = \
            self.shuffled_indices.mem[offset - size:offset]

        if self.is_master:
            return
        self.fill_minibatch()
        self._normalize_minibatch()
        self._map_minibatch_labels()
        if size < self.max_minibatch_size:
            self._pad_tail(size)
        self.minibatch_data.unmap()
        self.minibatch_labels.unmap()
        self.minibatch_indices.unmap()

    def _pad_tail(self, size):
        """Zero-pad the tail minibatch so jitted consumers always see the
        same shape (ref: base.py:749-753 + TPU static-shape requirement).
        Device-gather loaders override the data part."""
        self.minibatch_data.mem[size:] = 0
        self.minibatch_labels.mem[size:] = -1
        self.minibatch_indices.mem[size:] = -1

    def _normalize_minibatch(self):
        size = self.minibatch_size
        self.minibatch_data.mem[:size] = self.normalizer.normalize(
            self.minibatch_data.mem[:size])

    def _map_minibatch_labels(self):
        if not self.labels_mapping:
            return
        for i, l in enumerate(self.raw_minibatch_labels[:self.minibatch_size]):
            if l is None:
                continue
            self.minibatch_labels.mem[i] = self.labels_mapping[l]

    def _class_by_offset(self, offset):
        for ci, end in enumerate(self._effective_end_offsets()):
            if offset < end:
                return ci, end - offset
        raise AssertionError("offset %d beyond dataset" % offset)

    def _effective_end_offsets(self):
        ends = list(self.class_end_offsets)
        ends[TRAIN] -= int(
            (1.0 - self.train_ratio) * self.class_lengths[TRAIN])
        return ends

    def _advance_global_offset(self):
        """Pick the next (offset, size); wraps + reshuffles at epoch end
        (ref: base.py:880)."""
        if self.is_slave:
            return self.minibatch_offset, self.minibatch_size
        if self.global_offset >= self.effective_total_samples:
            self.global_offset = 0
            self.shuffle()
        self.minibatch_class, remainder = self._class_by_offset(
            self.global_offset)
        size = min(remainder, self.max_minibatch_size)
        self.global_offset += size
        self.train_ended.set(
            self.global_offset >= self.effective_total_samples)
        return self.global_offset, size

    def _epoch_flag_values(self, minibatch_class, global_offset):
        """The (last_minibatch, epoch_ended) values one serve at
        ``global_offset`` in ``minibatch_class`` produces — shared by
        the live flag update below and the prefetch worker, which
        computes flags ahead of the waves without touching the gate
        Bools (loader/prefetch.py)."""
        class_ended = global_offset in self.class_end_offsets \
            or global_offset == self.effective_total_samples
        # in-flight jobs only gate the flags on the coordinator — in
        # standalone mode the just-served minibatch is still "pending"
        # at this point (ref: base.py:862-878)
        last_mb = (class_ended and not self.failed_minibatches
                   and (not self.is_master
                        or not any(self.pending_minibatches_.values())))
        epoch_ended = last_mb and (
            minibatch_class == VALID or
            (minibatch_class == TEST and
             self.class_lengths[TRAIN] == self.class_lengths[VALID] == 0) or
            (minibatch_class == TRAIN and
             self.class_lengths[VALID] == 0))
        return last_mb, epoch_ended

    def _update_flags(self):
        if self.is_slave:
            return
        last_mb, epoch_ended = self._epoch_flag_values(
            self.minibatch_class, self.global_offset)
        self.last_minibatch.set(last_mb)
        self.epoch_ended.set(epoch_ended)

    def _on_successful_serve(self):
        self.samples_served += self.minibatch_size
        if not self.is_slave and self.effective_total_samples:
            # workers get epoch_number from the coordinator; deriving it
            # from a worker's partial samples_served would clobber it
            self.epoch_number = \
                self.samples_served // self.effective_total_samples
        self._update_flags()
        # only clear the standalone (None) slot here: completed worker
        # jobs were already popped in apply_data_from_slave, and offsets
        # repeat across epochs so a blind scan could delete another
        # worker's identical in-flight job
        jobs = self.pending_minibatches_.get(None)
        if jobs and (self.minibatch_offset, self.minibatch_size) in jobs:
            jobs.remove((self.minibatch_offset, self.minibatch_size))

    # -- distributed contract (ref: base.py:628-687) ---------------------------

    def generate_data_for_slave(self, slave=None):
        self.serve_next_minibatch(slave)
        return {
            "indices": numpy.array(
                self.minibatch_indices.mem[:self.minibatch_size]),
            "minibatch_class": self.minibatch_class,
            "minibatch_size": self.minibatch_size,
            "minibatch_offset": self.minibatch_offset,
            "epoch_number": self.epoch_number,
        }

    def apply_data_from_master(self, data):
        for attr in ("minibatch_class", "minibatch_size",
                     "minibatch_offset", "epoch_number"):
            setattr(self, attr, data[attr])
        self.last_minibatch.set(False)
        self.epoch_ended.set(False)
        self.train_ended.set(False)
        indices = data["indices"]
        assert len(indices) == self.minibatch_size
        self.shuffled_indices.map_write()
        self.shuffled_indices.mem[
            self.minibatch_offset - self.minibatch_size:
            self.minibatch_offset] = indices

    def generate_data_for_master(self):
        return True

    def apply_data_from_slave(self, data, slave=None):
        jobs = self.pending_minibatches_.get(slave)
        if jobs:
            self.minibatch_offset, self.minibatch_size = jobs.pop()
            self._on_successful_serve()

    def drop_slave(self, slave=None):
        jobs = self.pending_minibatches_.pop(slave, None)
        if jobs:
            self.failed_minibatches.extend(jobs)
            self.info("requeued %d minibatch(es) from dropped worker %s",
                      len(jobs), slave)

    # -- results ---------------------------------------------------------------

    def get_metric_values(self):
        return {"Total epochs": self.epoch_number}
