"""Interactive loader (rebuild of veles/loader/interactive.py:57): a
queue-fed loader for serving/notebook use — callers push samples with
:meth:`feed`, the graph consumes them as minibatches, and results are
read back from the forward units.  Pairs with RESTfulAPI the same way
the reference paired RestfulLoader (veles/loader/restful.py:52)."""

import queue

import numpy

from veles_tpu.loader.base import TEST, Loader


class InteractiveLoader(Loader):
    """Samples arrive at run time; every minibatch is TEST class (no
    labels, no epochs — the graph loops while the feed stays open)."""

    #: serving blocks on a live request queue — there is nothing to
    #: produce ahead of the waves (and run() is overridden anyway)
    prefetchable = False

    def __init__(self, workflow, sample_shape=None, max_wait=30.0,
                 **kwargs):
        super(InteractiveLoader, self).__init__(workflow, **kwargs)
        if sample_shape is None:
            raise ValueError("sample_shape is required")
        self.sample_shape = tuple(sample_shape)
        self.max_wait = max_wait

    def init_unpickled(self):
        super(InteractiveLoader, self).init_unpickled()
        self._queue_ = queue.Queue()
        self._closed_ = False

    # -- feeding --------------------------------------------------------------

    def feed(self, sample):
        """Queue one sample (numpy, matching sample_shape)."""
        sample = numpy.asarray(sample, numpy.float32)
        if sample.shape != self.sample_shape:
            raise ValueError("sample shape %s != %s"
                             % (sample.shape, self.sample_shape))
        self._queue_.put(sample)

    def close(self):
        """No more samples — the workflow's loop gate should close."""
        self._closed_ = True
        self._queue_.put(None)

    @property
    def closed(self):
        return self._closed_

    # -- ILoader --------------------------------------------------------------

    def load_data(self):
        # an unbounded interactive stream: advertise one TEST "sample"
        # so the epoch machinery has a non-empty space to walk; serving
        # blocks on the queue instead of indexing a dataset
        self.class_lengths[:] = [1, 0, 0]

    def create_minibatch_data(self):
        self.minibatch_data.reset(numpy.zeros(
            (self.max_minibatch_size,) + self.sample_shape,
            numpy.float32))

    def fill_minibatch(self):
        pass  # serving happens in run()

    def run(self):
        """Block for at least one sample, then drain up to a full
        minibatch."""
        samples = []
        try:
            first = self._queue_.get(timeout=self.max_wait)
        except queue.Empty:
            # idle feed: serve an empty minibatch WITHOUT closing — only
            # close() ends the stream (an idle REST endpoint must keep
            # serving later requests)
            first = None
        if first is not None:
            samples.append(first)
            while len(samples) < self.max_minibatch_size:
                try:
                    s = self._queue_.get_nowait()
                except queue.Empty:
                    break
                if s is None:
                    self._closed_ = True
                    break
                samples.append(s)
        self.minibatch_class = TEST
        self.minibatch_size = len(samples)
        self.minibatch_data.map_invalidate()
        self.minibatch_data.mem[:] = 0
        for i, s in enumerate(samples):
            self.minibatch_data.mem[i] = s
        self.minibatch_data.unmap()
        self.samples_served += len(samples)
        self.last_minibatch.set(True)
        self.epoch_ended.set(self._closed_)
