"""Datasets from pickle files (rebuild of veles/loader/pickles.py:55).

Each of the three classes (test/validation/train) is an optional pickle
file containing either an ndarray [n, ...] or a tuple/dict of
``(data, labels)``.
"""

import gzip
import pickle

import numpy

from veles_tpu.loader.base import TEST, TRAIN, VALID
from veles_tpu.loader.fullbatch import FullBatchLoader


def _load_pickle(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        return pickle.load(f)


def _split(obj):
    if isinstance(obj, dict):
        return numpy.asarray(obj["data"]), obj.get("labels")
    if isinstance(obj, (tuple, list)) and len(obj) == 2:
        return numpy.asarray(obj[0]), obj[1]
    return numpy.asarray(obj), None


class PicklesLoader(FullBatchLoader):
    """test/validation/train pickles → device-resident dataset
    (ref: loader/pickles.py:55)."""

    def __init__(self, workflow, test_path=None, validation_path=None,
                 train_path=None, **kwargs):
        super(PicklesLoader, self).__init__(workflow, **kwargs)
        self.paths = {TEST: test_path, VALID: validation_path,
                      TRAIN: train_path}

    def load_data(self):
        datas, labels = [], []
        for ci in (TEST, VALID, TRAIN):
            path = self.paths[ci]
            if not path:
                self.class_lengths[ci] = 0
                continue
            data, lbls = _split(_load_pickle(path))
            self.class_lengths[ci] = len(data)
            datas.append(data)
            labels.append(list(lbls) if lbls is not None
                          else [None] * len(data))
        if not datas:
            raise ValueError("no pickle paths given")
        self.original_data = numpy.concatenate(datas, axis=0)
        flat = [l for ls in labels for l in ls]
        self.original_labels = None \
            if all(l is None for l in flat) else flat
