"""Asynchronous input pipeline — prefetch + host↔device overlap for
streaming loaders.

The reference Veles hid input latency behind its thread-pool dataflow
engine: loader units decoded the next minibatch while trainer units ran
the current one between gate waves.  Our deterministic worklist
scheduler serialized them — every wave paid ``fill_minibatch()`` (host
decode), normalization and the host→HBM upload *before* the trainer
could dispatch, which caps throughput at ``1/(decode + step)`` on every
streaming loader (image / text / hdf5 / pickles / sound).  JAX's async
dispatch makes the fix cheap: while step *k* computes, this pipeline
decodes batch *k+1..k+depth* on a background thread and uploads them
from a second one, so the wave consumes an **already-on-device batch
handle** and throughput becomes ``1/max(decode, step)``.

Three decoupled stages over a rotating pool of host staging buffers:

1. **fill** — a worker thread walks the loader's serving state machine
   ahead of the waves (shadow copies of ``global_offset`` /
   ``samples_served`` / the shuffle permutation, using the loader's own
   prng so the schedule is bit-for-bit the synchronous one) and runs
   ``fill_minibatch`` + normalization + label mapping + tail padding
   against a :class:`_StageView` — a stand-in ``self`` whose
   ``minibatch_*`` attributes point at pooled staging buffers, so the
   loader's live ``minibatch_data`` mirror is never mutated mid-step;
2. **upload** — a second thread issues the host→device transfer for
   each staged batch (through the trainer's input sharding when one is
   registered, see :meth:`PrefetchPipeline.set_placement`) and funnels
   it through a tiny jitted copy: ``jax.device_put`` may *alias* the
   numpy staging buffer (it does on the CPU backend), and an aliased
   buffer must never be recycled while a step may still read it — the
   copy gives the device an independent buffer and bounds the staging
   pool at ``depth + 3`` sets;
3. **pop** — the loader's ``run()`` (main thread) dequeues the next
   ready record and *replays* it: scalar walk state, the minibatch
   arrays (installed zero-copy via :meth:`Array.adopt`) and — last —
   the ``last_minibatch`` / ``epoch_ended`` / ``train_ended`` gate
   Bools, so the flag sequence the Decision unit observes is identical
   to the synchronous path's.

Teardown: ``Loader.stop()`` (fired by ``Workflow.stop`` on halt) joins
both threads; a worker exception is forwarded through the queue and
re-raised on the main thread at the next pop (after an eager close, so
the flight recorder's thread dump shows no orphaned workers).  Both
loops also watch a weakref to the loader and exit when it is collected.

Config: ``root.common.loader.prefetch`` ``{enabled, depth}`` (CLI:
``--prefetch N``); ``depth<=0`` or any non-standalone / cross-process /
failed-minibatch situation falls back to the synchronous path.
"""

import queue
import threading
import weakref

import jax
import jax.numpy as jnp
import numpy

from veles_tpu.memory import Array, DEV_DIRTY
from veles_tpu.loader.base import (
    INDEX_DTYPE, LABEL_DTYPE, TRAIN, VALID)

#: how long blocking queue ops wait before re-checking liveness (s)
_TICK = 0.1
#: pop gives up after this long without a batch AND without live
#: workers (a stall with live workers keeps waiting — a slow decode
#: is not an error)
_DEAD_POLL = 0.5


def _prefetch_metrics():
    from veles_tpu.telemetry import metrics
    return (
        metrics.gauge(
            "veles_prefetch_depth",
            "configured prefetch depth (ready-queue capacity) per "
            "loader", ("loader",)),
        metrics.gauge(
            "veles_prefetch_occupancy",
            "ready batches waiting in the prefetch queue at pop time "
            "(0 = the trainer outruns the decode; depth = fully "
            "hidden input latency)", ("loader",)),
        metrics.counter(
            "veles_prefetch_batches_total",
            "minibatches served through the asynchronous input "
            "pipeline", ("loader",)),
    )


_copy_lock = threading.Lock()
_copy_fn = None


def _device_copy():
    """The jitted identity-copy every prefetched upload funnels
    through.  ``jax.device_put(numpy_buffer)`` may alias the host
    buffer (CPU backend) — the staging pool would then corrupt
    in-flight batches on reuse; ``copy_p`` forces an independent
    device buffer.  One process-wide instance so every pipeline
    shares the compile cache."""
    global _copy_fn
    with _copy_lock:
        if _copy_fn is None:
            from veles_tpu.telemetry import track_jit
            _copy_fn = track_jit(
                "loader.prefetch_copy",
                jax.jit(lambda x: jnp.copy(x)))
        return _copy_fn


class _BufferSet(object):
    """One rotation slot of the host staging pool: staged Arrays for
    the fill stage to write into, matching the loader's minibatch
    array shapes/dtypes."""

    __slots__ = ("data", "labels", "indices", "targets", "raw_labels")

    def __init__(self, loader):
        self.data = Array(numpy.zeros(
            loader.minibatch_data.shape, loader.minibatch_data.dtype))
        self.labels = Array(numpy.zeros(
            loader.minibatch_labels.shape
            or (loader.max_minibatch_size,),
            loader.minibatch_labels.dtype or LABEL_DTYPE))
        self.indices = Array(numpy.zeros(
            (loader.max_minibatch_size,), INDEX_DTYPE))
        targets = getattr(loader, "minibatch_targets", None)
        self.targets = None
        if isinstance(targets, Array) and bool(targets):
            self.targets = Array(numpy.zeros(targets.shape,
                                             targets.dtype))
        self.raw_labels = [None] * loader.max_minibatch_size


def _make_stage(loader, bufs):
    """Stand-in ``self`` for the subclass fill path
    (``fill_minibatch`` / ``_normalize_minibatch`` /
    ``_map_minibatch_labels`` / ``_pad_tail``): a REAL instance of
    the loader's class (``__init__`` bypassed) whose ``__dict__`` is
    a shallow copy of the live unit's with the ``minibatch_*``
    attributes re-pointed at pooled staging buffers — so the live
    ``minibatch_data`` mirror is never mutated mid-step, while
    ``isinstance`` checks, properties and ``super()`` calls inside
    subclass fill paths keep working.  Dataset storage, the
    normalizer and class offsets are shared by reference (reads);
    attribute WRITES land on the stage's own ``__dict__`` so a
    subclass assigning scratch state on ``self`` cannot race the
    live unit."""
    from veles_tpu.mutable import unshadow
    stage = object.__new__(unshadow(type(loader)))
    stage.__dict__.update(loader.__dict__)
    stage.__dict__.pop("_linked_attrs_", None)
    stage.minibatch_data = bufs.data
    stage.minibatch_labels = bufs.labels
    stage.minibatch_indices = bufs.indices
    if bufs.targets is not None:
        stage.minibatch_targets = bufs.targets
    stage.raw_minibatch_labels = bufs.raw_labels
    return stage


class _Record(object):
    """One produced minibatch: staged buffers + uploaded device
    arrays + the post-serve scalar/flag state to replay at pop."""

    __slots__ = ("bufs", "cls", "size", "offset", "global_offset",
                 "samples_served", "epoch_number", "shuffle_limit",
                 "train_ended", "last_minibatch", "epoch_ended",
                 "permutation", "dev_data", "dev_labels",
                 "dev_targets", "data_dev_dirty", "targets_dev_dirty",
                 "error")

    def __init__(self, error=None):
        self.error = error
        self.permutation = None
        self.dev_data = None
        self.dev_labels = None
        self.dev_targets = None
        self.data_dev_dirty = False
        self.targets_dev_dirty = False


class PrefetchPipeline(object):
    """The double/triple-buffered asynchronous input pipeline (module
    docstring).  Owned by a :class:`~veles_tpu.loader.base.Loader`
    as the volatile ``prefetch_`` attribute; created lazily on the
    first streaming ``run()`` when the config enables it."""

    def __init__(self, loader, depth):
        self.depth = max(1, int(depth))
        self.loader_name = loader.name
        self._loader_ref = weakref.ref(loader)
        self._stop = threading.Event()
        self._installed = None

        # shadow walk state — the worker advances these ahead of the
        # waves; the loader's own attributes stay at the last POPPED
        # batch so snapshots capture a resumable position
        loader.shuffled_indices.map_read()
        self._indices = numpy.array(loader.shuffled_indices.mem)
        self._offset = int(loader.global_offset)
        self._samples = int(loader.samples_served)
        self._shuffle_limit = loader.shuffle_limit
        self._pending_perm = None

        # placement: None → plain device_put to the array's bound (or
        # default) device, matching the synchronous Array._upload;
        # the trainer registers its input NamedShardings here so the
        # upload lands pre-sharded (set_placement)
        self._data_sharding = None
        self._labels_sharding = None
        self._targets_sharding = None
        self._data_device = getattr(
            loader.minibatch_data, "_device_", None)

        self._free = queue.Queue()
        for _ in range(self.depth + 3):
            self._free.put(_BufferSet(loader))
        self._filled = queue.Queue(maxsize=1)
        self._ready = queue.Queue(maxsize=self.depth)

        depth_g, self._occupancy_g, self._batches_c = \
            _prefetch_metrics()
        depth_g.labels(self.loader_name).set(self.depth)
        self._occupancy_g = self._occupancy_g.labels(self.loader_name)
        self._batches_c = self._batches_c.labels(self.loader_name)

        self._fill_thread = threading.Thread(
            target=self._fill_loop, daemon=True,
            name="prefetch-fill:%s" % self.loader_name)
        self._upload_thread = threading.Thread(
            target=self._upload_loop, daemon=True,
            name="prefetch-upload:%s" % self.loader_name)
        self._fill_thread.start()
        self._upload_thread.start()

    # -- placement -----------------------------------------------------------

    def set_placement(self, data_sharding, labels_sharding=None,
                      targets_sharding=None):
        """Trainer hook: upload batches straight into the fused step's
        input shardings (parallel.sharding.put) so the dispatch-time
        re-place is a no-op.  Idempotent; applies from the next
        upload."""
        self._data_sharding = data_sharding
        self._labels_sharding = labels_sharding
        self._targets_sharding = targets_sharding

    # -- the fill stage (worker thread) ---------------------------------------

    def _shuffle_shadow(self, loader):
        """The epoch-wrap reshuffle against the shadow permutation —
        same prng stream, same call order as Loader.shuffle(), so the
        schedule stays bit-identical to the synchronous path."""
        if loader.class_lengths[TRAIN] == 0:
            return
        if self._shuffle_limit is not None:
            if self._shuffle_limit <= 0:
                return
            self._shuffle_limit -= 1
        loader.prng.shuffle(
            self._indices[loader.class_end_offsets[VALID]:])
        # pop installs this copy into loader.shuffled_indices at the
        # first batch of the new epoch — exactly when the sync path's
        # shuffle would have become visible
        self._pending_perm = numpy.array(self._indices)

    def _produce_into(self, loader, bufs):
        total = loader.effective_total_samples
        if self._offset >= total:
            self._offset = 0
            self._shuffle_shadow(loader)
        cls, remainder = loader._class_by_offset(self._offset)
        size = min(remainder, loader.max_minibatch_size)
        self._offset += size
        offset = self._offset
        self._samples += size

        rec = _Record()
        rec.bufs = bufs
        rec.cls = cls
        rec.size = size
        rec.offset = offset
        rec.global_offset = self._offset
        rec.samples_served = self._samples
        rec.epoch_number = self._samples // total if total else 0
        rec.shuffle_limit = self._shuffle_limit
        rec.train_ended = self._offset >= total
        rec.last_minibatch, rec.epoch_ended = \
            loader._epoch_flag_values(cls, self._offset)
        rec.permutation, self._pending_perm = self._pending_perm, None

        stage = _make_stage(loader, bufs)
        stage.minibatch_offset = offset
        stage.minibatch_size = size
        stage.minibatch_class = cls
        bufs.indices.mem[:size] = self._indices[offset - size:offset]
        stage.fill_minibatch()
        stage._normalize_minibatch()
        stage._map_minibatch_labels()
        if size < loader.max_minibatch_size:
            stage._pad_tail(size)
        return rec

    def _fill_loop(self):
        while not self._stop.is_set():
            bufs = self._q_get(self._free)
            if bufs is None:
                break
            loader = self._loader_ref()
            if loader is None:
                break
            try:
                rec = self._produce_into(loader, bufs)
            except BaseException as e:  # noqa: B036 — forwarded to pop
                del loader
                self._q_put(self._filled, _Record(error=e))
                break
            del loader
            if not self._q_put(self._filled, rec):
                break

    # -- the upload stage (uploader thread) -----------------------------------

    def _put_copy(self, mem, sharding):
        """Host staging buffer → independent device buffer: place
        (sharded when the trainer registered one), then the jitted
        copy (see _device_copy); block only for the transfer — this
        thread is off the wave's critical path."""
        if sharding is not None:
            from veles_tpu.parallel import sharding as shlib
            staged = shlib.put(mem, sharding)
        elif self._data_device is not None:
            staged = jax.device_put(mem,
                                    self._data_device.jax_device)
        else:
            staged = jax.device_put(mem)
        out = _device_copy()(staged)
        out.block_until_ready()
        return out

    def _upload_rec(self, rec):
        data = rec.bufs.data
        if data._devmem_ is not None and data._state == DEV_DIRTY:
            # a device-gather fill (FullBatchLoader host-fallback
            # variants) already produced a device buffer — adopt it
            rec.dev_data = data._devmem_
            rec.data_dev_dirty = True
            data._devmem_ = None
        else:
            rec.dev_data = self._put_copy(data.mem,
                                          self._data_sharding)
        rec.dev_labels = self._put_copy(rec.bufs.labels.mem,
                                        self._labels_sharding)
        if rec.bufs.targets is not None:
            tgt = rec.bufs.targets
            if tgt._devmem_ is not None and tgt._state == DEV_DIRTY:
                rec.dev_targets = tgt._devmem_
                rec.targets_dev_dirty = True
                tgt._devmem_ = None
            else:
                rec.dev_targets = self._put_copy(
                    tgt.mem, self._targets_sharding)

    def _upload_loop(self):
        while not self._stop.is_set():
            rec = self._q_get(self._filled)
            if rec is None:
                break
            if rec.error is None:
                try:
                    self._upload_rec(rec)
                except BaseException as e:  # noqa: B036
                    rec = _Record(error=e)
            if not self._q_put(self._ready, rec):
                break
            if rec.error is not None:
                break

    # -- the pop stage (main thread, Loader.run) ------------------------------

    def pop_into(self, loader):
        """Dequeue the next ready batch and replay it onto the live
        loader: scalar walk state, buffers (zero-copy Array.adopt),
        then the gate Bools — identical observable sequence to one
        synchronous serve."""
        self._occupancy_g.set(self._ready.qsize())
        while True:
            try:
                rec = self._ready.get(timeout=_DEAD_POLL)
                break
            except queue.Empty:
                if self._stop.is_set() or not (
                        self._fill_thread.is_alive()
                        and self._upload_thread.is_alive()):
                    self.close()
                    raise RuntimeError(
                        "prefetch pipeline for %s died without "
                        "delivering a batch" % self.loader_name)
        if rec.error is not None:
            # tear down BEFORE re-raising: the flight recorder's
            # thread dump must show no orphaned prefetch workers
            self.close()
            raise rec.error
        if self._installed is not None:
            self._free.put(self._installed.bufs)
        self._installed = rec

        loader.minibatch_class = rec.cls
        loader.minibatch_offset = rec.offset
        loader.minibatch_size = rec.size
        loader.global_offset = rec.global_offset
        loader.samples_served = rec.samples_served
        if not loader.is_slave:
            loader.epoch_number = rec.epoch_number
        loader.shuffle_limit = rec.shuffle_limit
        if rec.permutation is not None:
            loader.shuffled_indices.mem = rec.permutation

        loader.minibatch_data.adopt(
            rec.bufs.data.mem, rec.dev_data,
            dev_dirty=rec.data_dev_dirty)
        loader.minibatch_labels.adopt(rec.bufs.labels.mem,
                                      rec.dev_labels)
        loader.minibatch_indices.adopt(rec.bufs.indices.mem)
        if rec.bufs.targets is not None:
            loader.minibatch_targets.adopt(
                rec.bufs.targets.mem, rec.dev_targets,
                dev_dirty=rec.targets_dev_dirty)
        loader.raw_minibatch_labels = rec.bufs.raw_labels

        # flags LAST — successors (Decision) read them after this wave
        loader.train_ended.set(rec.train_ended)
        loader.last_minibatch.set(rec.last_minibatch)
        loader.epoch_ended.set(rec.epoch_ended)
        self._batches_c.inc()

    # -- liveness-aware queue helpers ------------------------------------------

    def _q_get(self, q):
        while not self._stop.is_set():
            try:
                return q.get(timeout=_TICK)
            except queue.Empty:
                if self._loader_ref() is None:
                    self._stop.set()
        return None

    def _q_put(self, q, item):
        while not self._stop.is_set():
            try:
                q.put(item, timeout=_TICK)
                return True
            except queue.Full:
                if self._loader_ref() is None:
                    self._stop.set()
        return False

    # -- teardown --------------------------------------------------------------

    @property
    def alive(self):
        return self._fill_thread.is_alive() \
            or self._upload_thread.is_alive()

    def close(self, timeout=5.0):
        """Stop both workers and join them (idempotent).  Queue ops
        poll the stop event every _TICK, so even a blocked put/get
        exits within one tick; a worker stuck inside a slow user
        fill_minibatch finishes that batch first."""
        self._stop.set()
        for t in (self._fill_thread, self._upload_thread):
            if t.is_alive() and t is not threading.current_thread():
                t.join(timeout)
        self._occupancy_g.set(0)
