"""HDFS text loader (rebuild of veles/loader/hdfs_loader.py:48).

The reference streamed newline-delimited text records from HDFS for the
Mastodon bridge; this implementation speaks **WebHDFS** (the REST
gateway every Hadoop distribution ships) via urllib — no Java client
needed.  Records are parsed by a pluggable ``parse(line) -> (features,
label)`` callable (default: whitespace-separated floats, last column =
label)."""

import json
import urllib.parse
import urllib.request

import numpy

from veles_tpu.loader.fullbatch import FullBatchLoader


def default_parse(line):
    parts = line.split()
    return [float(v) for v in parts[:-1]], parts[-1]


class WebHDFSClient:
    """Minimal WebHDFS API (LISTSTATUS + OPEN)."""

    def __init__(self, namenode, user=None, timeout=30):
        self.base = "http://%s/webhdfs/v1" % namenode
        self.user = user
        self.timeout = timeout

    def _url(self, path, op, **params):
        q = {"op": op}
        if self.user:
            q["user.name"] = self.user
        q.update(params)
        return "%s%s?%s" % (self.base, path, urllib.parse.urlencode(q))

    def listdir(self, path):
        with urllib.request.urlopen(self._url(path, "LISTSTATUS"),
                                    timeout=self.timeout) as r:
            statuses = json.load(r)["FileStatuses"]["FileStatus"]
        return [(s["pathSuffix"], s["type"]) for s in statuses]

    def read(self, path):
        with urllib.request.urlopen(self._url(path, "OPEN"),
                                    timeout=self.timeout) as r:
            return r.read()


class HDFSTextLoader(FullBatchLoader):
    """Reads every file under the class paths and parses lines into
    (features, label) rows (ref: hdfs_loader.py:48)."""

    def __init__(self, workflow, namenode=None, user=None,
                 test_path=None, validation_path=None, train_path=None,
                 parse=default_parse, **kwargs):
        super(HDFSTextLoader, self).__init__(workflow, **kwargs)
        if namenode is None:
            raise ValueError("namenode host:port is required")
        self.namenode = namenode
        self.user = user
        self.class_paths = [test_path, validation_path, train_path]
        self.parse = parse

    def _files_under(self, client, path):
        out = []
        for suffix, kind in client.listdir(path):
            full = path.rstrip("/") + "/" + suffix if suffix else path
            if kind == "DIRECTORY":
                out.extend(self._files_under(client, full))
            else:
                out.append(full)
        return sorted(out)

    def load_data(self):
        client = WebHDFSClient(self.namenode, self.user)
        rows, labels = [], []
        for ci, path in enumerate(self.class_paths):
            count = 0
            if path:
                for f in self._files_under(client, path):
                    text = client.read(f).decode()
                    for line in text.splitlines():
                        line = line.strip()
                        if not line:
                            continue
                        feats, label = self.parse(line)
                        rows.append(feats)
                        labels.append(label)
                        count += 1
            self.class_lengths[ci] = count
        if not rows:
            raise ValueError("%s: no records under %s" %
                             (self, self.class_paths))
        self.original_data = numpy.asarray(rows, numpy.float32)
        if any(l is not None for l in labels):
            # original_labels stays RAW — fullbatch._post_load applies
            # labels_mapping (pre-mapping would double-map to -1)
            self.original_labels = labels
            if not all(isinstance(l, (int, numpy.integer))
                       for l in labels):
                self.labels_mapping = {
                    l: i for i, l in enumerate(sorted(set(labels)))}
