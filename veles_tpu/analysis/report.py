"""Text and JSON reporters for veles-lint findings."""

import json


def render_text(findings, stale=(), show_baselined=False):
    lines = []
    fresh = [f for f in findings if not f.baselined]
    for f in fresh:
        lines.append(str(f))
    if show_baselined:
        for f in findings:
            if f.baselined:
                lines.append(str(f))
    if stale:
        lines.append("")
        lines.append("stale baseline entries (match no finding — "
                     "prune them):")
        for key in stale:
            lines.append("  " + key)
    by_code = {}
    for f in fresh:
        by_code[f.code] = by_code.get(f.code, 0) + 1
    summary = ", ".join("%s: %d" % kv for kv in sorted(by_code.items()))
    n_base = sum(1 for f in findings if f.baselined)
    lines.append("")
    lines.append("%d finding(s) (%s)%s%s" % (
        len(fresh), summary or "clean",
        ", %d baselined" % n_base if n_base else "",
        ", %d stale baseline entr(ies)" % len(stale) if stale else ""))
    return "\n".join(lines)


def render_json(findings, stale=(), errors=()):
    return json.dumps({
        "findings": [f.as_dict() for f in findings],
        "unbaselined": sum(1 for f in findings if not f.baselined),
        "baselined": sum(1 for f in findings if f.baselined),
        "stale_baseline": list(stale),
        "parse_errors": [{"path": p, "error": e} for p, e in errors],
    }, indent=2, sort_keys=True)
