"""Shared infrastructure for the veles-lint passes.

Everything here is pure stdlib ``ast`` work — importing this package
must never pull in jax (the tier-1 run-clean gate executes with no
accelerator runtime at all), so passes receive pre-parsed
:class:`Module` objects and report :class:`Finding`s instead of
touching the live framework.

A **pass** subclasses :class:`Pass` and implements :meth:`Pass.run`
(per module) and/or :meth:`Pass.finalize` (whole-project, for
cross-module facts like dead config keys).  Findings are keyed for the
baseline by ``(code, path, context, detail)`` — never by line number,
so unrelated edits don't churn the baseline file.
"""

import ast
import dataclasses
from pathlib import Path

__all__ = ["Finding", "Module", "Project", "Pass", "run_passes",
           "dotted", "parent_chain", "attach_parents", "ScopeTracker"]


@dataclasses.dataclass
class Finding:
    """One reported hazard.

    ``context`` is the enclosing ``Class.method`` / function qualname
    (``<module>`` at top level); ``detail`` the stable token the
    finding is about (attribute name, config key, callee...).  The
    pair keys the baseline: line numbers deliberately do not."""

    code: str
    path: str          # repo-relative posix path
    line: int
    col: int
    context: str
    detail: str
    message: str
    baselined: bool = False
    reason: str = ""   # baseline reason, when baselined

    @property
    def key(self):
        return "%s %s::%s::%s" % (self.code, self.path, self.context,
                                  self.detail)

    def as_dict(self):
        return {
            "code": self.code, "path": self.path, "line": self.line,
            "col": self.col, "context": self.context,
            "detail": self.detail, "message": self.message,
            "key": self.key, "baselined": self.baselined,
            "reason": self.reason or None,
        }

    def __str__(self):
        mark = " [baselined: %s]" % self.reason if self.baselined else ""
        return "%s:%d:%d: %s [%s] %s%s" % (
            self.path, self.line, self.col, self.code, self.context,
            self.message, mark)


class Module:
    """One parsed source file: text, AST (with parent links), and the
    repo-relative path every finding reports."""

    def __init__(self, path, relpath, text=None):
        self.path = Path(path)
        self.relpath = str(relpath)
        self.text = self.path.read_text() if text is None else text
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=self.relpath)
        attach_parents(self.tree)

    @property
    def imports_threading(self):
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                if any(a.name.split(".")[0] == "threading"
                       for a in node.names):
                    return True
            elif isinstance(node, ast.ImportFrom):
                if (node.module or "").split(".")[0] == "threading":
                    return True
        return False


class Project:
    """The scanned module set plus a scratch dict passes share
    (e.g. the C-pass stores config declarations here)."""

    def __init__(self, modules):
        self.modules = list(modules)
        self.shared = {}

    def module(self, relpath):
        for m in self.modules:
            if m.relpath == relpath:
                return m
        return None


class Pass:
    """Base class: ``CODES`` maps each finding code to its one-line
    description (the docs and ``--list-codes`` render from it)."""

    NAME = "?"
    CODES = {}

    def run(self, module, project):
        """Per-module findings (may also stash facts in
        ``project.shared`` for :meth:`finalize`)."""
        return []

    def finalize(self, project):
        """Whole-project findings, after every module ran."""
        return []

    def finding(self, module, node, code, context, detail, message):
        return Finding(code=code, path=module.relpath,
                       line=getattr(node, "lineno", 0),
                       col=getattr(node, "col_offset", 0),
                       context=context, detail=detail, message=message)


# -- AST helpers -------------------------------------------------------------

def attach_parents(tree):
    """Annotate every node with ``_parent`` (None at the root)."""
    tree._parent = None
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child._parent = node
    return tree


def parent_chain(node):
    """The node's ancestors, innermost first."""
    node = getattr(node, "_parent", None)
    while node is not None:
        yield node
        node = getattr(node, "_parent", None)


def dotted(node):
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(node):
    """Dotted callee name of a Call, else None."""
    return dotted(node.func) if isinstance(node, ast.Call) else None


def enclosing_function(node):
    """The innermost FunctionDef/AsyncFunctionDef containing ``node``
    (None at module level)."""
    for p in parent_chain(node):
        if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return p
    return None


def qualname_of(node):
    """``Class.method`` / ``fn.<locals>.inner`` style context string
    for the statement containing ``node``."""
    names = []
    for p in parent_chain(node):
        if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            names.append(p.name)
    return ".".join(reversed(names)) or "<module>"


def with_lock_names(node):
    """Names of every lock guarding ``node``: for each enclosing
    ``with X:`` / ``with X(...):``, the dotted name of X (call or
    bare).  ``with self._lock:``, ``with lock:``, ``with
    self._cv:`` all count — lock identity is checked by the caller."""
    held = []
    for p in parent_chain(node):
        if isinstance(p, (ast.With, ast.AsyncWith)):
            for item in p.items:
                ctx = item.context_expr
                name = dotted(ctx) or call_name(ctx)
                if name:
                    held.append(name)
    return held


class ScopeTracker(ast.NodeVisitor):
    """Visitor base that maintains ``self.scope`` — a list of
    enclosing (kind, name) pairs — while walking the tree.  Passes
    subclass it instead of re-implementing qualname bookkeeping."""

    def __init__(self):
        self.scope = []

    @property
    def qualname(self):
        return ".".join(n for _, n in self.scope) or "<module>"

    @property
    def enclosing_class(self):
        for kind, name in reversed(self.scope):
            if kind == "class":
                return name
        return None

    def visit_ClassDef(self, node):
        self.scope.append(("class", node.name))
        self.generic_visit(node)
        self.scope.pop()

    def _visit_func(self, node):
        self.scope.append(("function", node.name))
        self.generic_visit(node)
        self.scope.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func


def collect_modules(paths, root=None):
    """Parse every ``*.py`` under ``paths`` into Modules.  ``root``
    anchors the repo-relative names (defaults to the common parent of
    the scanned paths' package)."""
    files = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            files.extend(sorted(p.rglob("*.py")))
        elif p.suffix == ".py":
            files.append(p)
    files = [f for f in files if "__pycache__" not in f.parts]
    if root is None:
        root = Path(common_root(files)) if files else Path.cwd()
    modules = []
    errors = []
    for f in files:
        try:
            rel = f.resolve().relative_to(Path(root).resolve())
        except ValueError:
            rel = f.name
        try:
            modules.append(Module(f, Path(rel).as_posix()))
        except SyntaxError as e:
            errors.append((Path(rel).as_posix(), str(e)))
    return modules, errors


def common_root(files):
    parts = None
    for f in files:
        fp = f.resolve().parent.parts
        if parts is None:
            parts = list(fp)
        else:
            n = 0
            for a, b in zip(parts, fp):
                if a != b:
                    break
                n += 1
            parts = parts[:n]
    return str(Path(*parts)) if parts else "."


def run_passes(passes, modules):
    """Run every pass over every module; returns (findings, project)."""
    project = Project(modules)
    findings = []
    for p in passes:
        for m in project.modules:
            findings.extend(p.run(m, project))
    for p in passes:
        findings.extend(p.finalize(project))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    return findings, project
