"""veles-lint — AST hazard analysis tuned to this codebase.

Four pass families over pure ``ast`` (no jax import anywhere in this
package — the tier-1 run-clean gate executes without an accelerator
runtime):

- **D-series** (``passes/donation.py``) — donated-buffer/host-view
  aliasing, the XLA:CPU heap-corruption family (ROUND6_NOTES.md);
- **T-series** (``passes/purity.py``) — side effects and tracer
  concretization inside jitted functions, untracked ``jax.jit``
  sites (subsumes the old tests/test_jit_guard.py);
- **L-series** (``passes/locks.py``) — unlocked shared writes and
  check-then-act races in the threaded modules;
- **C-series** (``passes/config_keys.py``) — every ``root.common.*``
  access must resolve to a key declared in ``config.py``; dead
  defaults are flagged too.

Run it::

    python -m veles_tpu.analysis [--strict] [--format json] [paths...]

Accepted findings live in ``baseline.txt`` (see ``baseline.py`` for
the format — every entry carries a reason).  ``docs/static_analysis.md``
is the operator guide.
"""

from veles_tpu.analysis.baseline import (
    DEFAULT_BASELINE, apply_baseline, format_entry, load_baseline)
from veles_tpu.analysis.core import (
    Finding, Module, Pass, Project, collect_modules, run_passes)
from veles_tpu.analysis.passes import ALL_CODES, ALL_PASSES
from veles_tpu.analysis.report import render_json, render_text

__all__ = [
    "ALL_CODES", "ALL_PASSES", "DEFAULT_BASELINE", "Finding",
    "Module", "Pass", "Project", "analyze", "apply_baseline",
    "collect_modules", "format_entry", "load_baseline", "render_json",
    "render_text", "run_passes",
]


def analyze(paths, root=None, baseline=None, passes=None):
    """One-call API: scan ``paths``, apply the baseline, and return
    ``(findings, fresh, stale, errors)`` where ``fresh`` are the
    unbaselined findings and ``stale`` the baseline keys matching
    nothing."""
    modules, errors = collect_modules(paths, root=root)
    findings, _ = run_passes(passes or ALL_PASSES, modules)
    entries = load_baseline(baseline) if baseline is not False \
        else {}
    fresh, stale = apply_baseline(findings, entries)
    return findings, fresh, stale, errors
