"""C-series — ``root.common.*`` config-key discipline.

``Config`` autovivifies: reading a mistyped key silently returns an
empty subtree (falsy) and writing one silently creates it, so typos
never crash — they just disable the feature they meant to configure.
The pass rebuilds the declared key tree from ``config.py``'s
``root.common.update({...})`` literal (plus any module-level
``root.common.X = ...`` assignments there) and checks every access in
the scanned tree against it:

- **C401** — a ``root.common...`` access (attribute chain read or
  write, ``.get("k")``, ``.get_dict("k")``, including one-hop
  forwarder helpers like ``_serving_conf`` and local aliases like
  ``cfg = root.common.health``) that does not resolve to a declared
  key.  An EMPTY dict literal in config.py declares an *open*
  subtree (user-supplied keys, e.g. ``publishing.confluence``) whose
  children all resolve.
- **C402** — a declared key that no scanned module ever reads (dead
  default).  Suppressed under subtrees consumed wholesale
  (``get_dict`` of the subtree, iteration, non-getter alias use) or
  read dynamically (``.get(variable)``).
"""

import ast

from veles_tpu.analysis.core import (
    Finding, Pass, call_name, dotted, qualname_of)

_GETTERS = ("get", "get_dict")
_NON_KEY_ATTRS = _GETTERS + ("update", "protect", "print_",
                             "__content__")


class _DeclTree:
    """Declared config keys under ``root.common``: ``leaves`` maps a
    dotted path to its declaration line, ``subtrees`` the interior
    nodes; an empty dict literal declares an OPEN subtree whose
    content is user-supplied."""

    def __init__(self):
        self.leaves = {}
        self.subtrees = {"": 0}
        self.open_subtrees = set()
        self.path = None      # config module relpath

    def declare_dict(self, node, prefix=""):
        for k, v in zip(node.keys, node.values):
            if not isinstance(k, ast.Constant) \
                    or not isinstance(k.value, str):
                continue
            path = ("%s.%s" % (prefix, k.value)) if prefix else k.value
            if isinstance(v, ast.Dict):
                self.subtrees[path] = k.lineno
                if not v.keys:
                    self.open_subtrees.add(path)
                self.declare_dict(v, path)
            else:
                self.leaves[path] = k.lineno

    def declare_leaf(self, path, lineno):
        parts = path.split(".")
        for i in range(1, len(parts)):
            self.subtrees.setdefault(".".join(parts[:i]), lineno)
        self.leaves[path] = lineno

    def resolves(self, path):
        if path in self.leaves or path in self.subtrees:
            return True
        parts = path.split(".")
        for i in range(len(parts), 0, -1):
            if ".".join(parts[:i]) in self.open_subtrees:
                return True
        return False


class _Access:
    """One config access: ``kind`` is ``read`` (leaf value), ``store``
    (validated, but not a read for dead-key purposes) or ``dynamic``
    (subtree consumed wholesale / non-literal key — suppresses C402
    below ``path``)."""

    __slots__ = ("path", "module", "node", "kind")

    def __init__(self, path, module, node, kind="read"):
        self.path = path
        self.module = module
        self.node = node
        self.kind = kind


class ConfigKeysPass(Pass):
    NAME = "config-keys"
    CODES = {
        "C401": "root.common.* access does not resolve to a key "
                "declared in config.py (autovivification hides the "
                "typo: the feature silently stays at its default)",
        "C402": "config key declared in config.py but never read "
                "anywhere in the scanned tree (dead default)",
    }

    def run(self, module, project):
        return []  # all work happens cross-module, in finalize()

    def finalize(self, project):
        decl = self._declarations(project)
        if decl is None:
            return []  # subset scan without config.py — nothing to do
        accesses = []
        for m in project.modules:
            if m.relpath == decl.path:
                continue
            accesses.extend(self._collect(m))
        findings = []
        dynamic_roots = set()
        read_paths = set()
        for a in accesses:
            if a.path and not decl.resolves(a.path):
                findings.append(Finding(
                    code="C401", path=a.module.relpath,
                    line=a.node.lineno, col=a.node.col_offset,
                    context=qualname_of(a.node), detail=a.path,
                    message="`root.common.%s` is not declared in "
                            "config.py — a typo here autovivifies an "
                            "empty node and the intended default "
                            "silently wins (declare the key with its "
                            "default)" % a.path))
            if a.kind == "dynamic":
                dynamic_roots.add(a.path)
            elif a.kind == "read":
                read_paths.add(a.path)
        for leaf, lineno in sorted(decl.leaves.items()):
            if leaf in read_paths:
                continue
            if any(leaf == d or leaf.startswith(d + ".")
                   for d in dynamic_roots):
                continue
            # an ancestor subtree consumed wholesale covers the leaf;
            # a read below the leaf means it is really a subtree
            if any(leaf.startswith(p + ".") or p.startswith(leaf + ".")
                   for p in read_paths):
                continue
            findings.append(Finding(
                code="C402", path=decl.path, line=lineno, col=0,
                context="<config>", detail=leaf,
                message="config key `root.common.%s` is declared "
                        "with a default but never read in the "
                        "scanned tree (dead default — wire it up or "
                        "drop it)" % leaf))
        return findings

    # -- declarations ------------------------------------------------------

    def _declarations(self, project):
        for m in project.modules:
            if not m.relpath.endswith("config.py") \
                    or "root.common.update" not in m.text:
                continue
            decl = _DeclTree()
            decl.path = m.relpath
            for node in ast.walk(m.tree):
                if isinstance(node, ast.Call) \
                        and dotted(node.func) == "root.common.update" \
                        and node.args \
                        and isinstance(node.args[0], ast.Dict):
                    decl.declare_dict(node.args[0])
                elif isinstance(node, ast.Assign):
                    for t in node.targets:
                        name = dotted(t) or ""
                        if name.startswith("root.common."):
                            decl.declare_leaf(
                                name[len("root.common."):],
                                node.lineno)
            return decl
        return None

    # -- access collection -------------------------------------------------

    @staticmethod
    def _chain_under_common(node):
        name = dotted(node)
        if name is None:
            return None
        if name == "root.common":
            return ""
        if name.startswith("root.common."):
            return name[len("root.common."):]
        return None

    def _collect(self, module):
        accesses = []
        aliases = self._aliases(module)        # (scope id, name) -> path
        alias_nodes = {}                       # Assign nodes to skip
        for (scope, name), (path, assign) in aliases.items():
            alias_nodes[id(assign.value)] = (scope, name, path)
        forwarders = self._forwarders(module)
        dynamic_aliases = self._dynamic_alias_uses(module, aliases)
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call):
                accesses.extend(self._call_access(
                    module, node, aliases, forwarders))
            elif isinstance(node, ast.Attribute):
                accesses.extend(self._attr_access(
                    module, node, alias_nodes))
        accesses.extend(dynamic_aliases)
        return accesses

    def _attr_access(self, module, node, alias_nodes):
        parent = getattr(node, "_parent", None)
        if isinstance(parent, ast.Attribute):
            return []  # not maximal: the outer chain reports
        path = self._chain_under_common(node)
        if not path:
            return []
        last = path.split(".")[-1]
        if last in _NON_KEY_ATTRS:
            return []  # receiver handled in _call_access
        if isinstance(getattr(node, "ctx", None), ast.Store):
            return [_Access(path, module, node, "store")]
        if id(node) in alias_nodes:
            # alias assignment: its literal .get uses are collected
            # at the call sites; non-getter uses were pre-collected
            # as dynamic
            return [_Access(path, module, node, "alias")]
        if isinstance(parent, ast.For) and parent.iter is node:
            return [_Access(path, module, node, "dynamic")]
        if isinstance(parent, ast.Assign) and parent.value is node:
            # a non-alias assignment of a whole subtree (e.g. into an
            # attribute) — consumed wholesale
            return [_Access(path, module, node, "dynamic")]
        return [_Access(path, module, node, "read")]

    def _call_access(self, module, node, aliases, forwarders):
        name = call_name(node)
        if name is None:
            return []
        fname = name.split(".")[-1]
        if fname in forwarders and node.args:
            base = forwarders[fname]
            k = node.args[0]
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                return [_Access("%s.%s" % (base, k.value) if base
                                else k.value, module, node)]
            return [_Access(base, module, node, "dynamic")]
        if fname not in _GETTERS \
                or not isinstance(node.func, ast.Attribute):
            return []
        base_node = node.func.value
        base = self._chain_under_common(base_node)
        if base is None:
            root_name = dotted(base_node)
            scope = self._scope_id(node)
            hit = aliases.get((scope, root_name)) \
                or aliases.get((None, root_name))
            if hit is None:
                return []
            base = hit[0]
        if not node.args:
            return []
        k = node.args[0]
        if isinstance(k, ast.Constant) and isinstance(k.value, str):
            path = "%s.%s" % (base, k.value) if base else k.value
            return [_Access(path, module, node)]
        return [_Access(base, module, node, "dynamic")]

    # -- alias helpers -----------------------------------------------------

    @staticmethod
    def _scope_id(node):
        from veles_tpu.analysis.core import enclosing_function
        fn = enclosing_function(node)
        return id(fn) if fn is not None else None

    def _aliases(self, module):
        """(scope id, name) -> (path, assign node) for ``cfg =
        root.common.<path>`` assignments."""
        out = {}
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Attribute):
                name = dotted(node.value) or ""
                if not name.startswith("root.common."):
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out[(self._scope_id(node), t.id)] = (
                            name[len("root.common."):], node)
        return out

    def _dynamic_alias_uses(self, module, aliases):
        """Alias names used OTHER than as ``alias.get("literal")``
        receivers consume the subtree wholesale — mark dynamic."""
        out = []
        by_scope = {}
        for (scope, name), (path, assign) in aliases.items():
            by_scope.setdefault(name, []).append((scope, path, assign))
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Name) \
                    or not isinstance(getattr(node, "ctx", None),
                                      ast.Load) \
                    or node.id not in by_scope:
                continue
            parent = getattr(node, "_parent", None)
            if isinstance(parent, ast.Attribute) \
                    and parent.attr in _GETTERS:
                continue  # getter receiver: handled per call site
            scope = self._scope_id(node)
            for ascope, path, assign in by_scope[node.id]:
                if ascope == scope:
                    out.append(_Access(path, module, node, "dynamic"))
        return out

    @staticmethod
    def _forwarders(module):
        """One-hop helpers: ``def f(name, default): return
        root.common.<p>.get(name, default)`` — call sites with a
        literal first argument then read ``<p>.<literal>``."""
        out = {}
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            rets = [s for s in ast.walk(node)
                    if isinstance(s, ast.Return)]
            if len(rets) != 1 or rets[0].value is None:
                continue
            call = rets[0].value
            if not isinstance(call, ast.Call):
                continue
            cname = call_name(call) or ""
            if not cname.startswith("root.common.") \
                    or cname.split(".")[-1] not in _GETTERS:
                continue
            if not call.args or not isinstance(call.args[0], ast.Name):
                continue
            params = [a.arg for a in node.args.args]
            if call.args[0].id not in params:
                continue
            base = cname[len("root.common."):]
            base = base.rsplit(".", 1)[0] if "." in base else ""
            out[node.name] = base
        return out
