"""M-series — metric-family hygiene at registry call sites.

The metrics registry is ``getLogger``-style get-or-create: modules
declare the families they touch without coordinating, and
``_get_or_create`` silently IGNORES the ``labelnames`` of every call
after the first — so two call sites declaring the same family with
different label sets never crash; whichever module imports first
wins, and the loser's ``.labels(...)`` calls raise (or, worse,
export under the wrong schema).  Likewise nothing enforces the
naming convention the dashboards/federation rollups key on.  This
pass checks both statically:

- **M501** — a registry family name (first argument of
  ``metrics.counter/gauge/histogram``) that is not ``veles_``-
  prefixed snake_case (``^veles(_[a-z0-9]+)+$``).  The federation
  merger, the fleet dashboards and the alert-rule grammar all select
  on the ``veles_`` namespace — an off-convention family is
  invisible to all of them.
- **M502** — one family declared with DIFFERENT label sets across
  call sites.  Only the first registration's ``labelnames`` takes
  effect, so every other declaration is dead text that will
  eventually disagree with reality.
- **M503** — a family declared with a ``tenant`` label in a module
  that never routes the label value through the admission-layer
  cardinality bounder (no ``….label(…)`` call anywhere in the
  module).  Tenant ids are CALLER-chosen strings; exporting them raw
  as label values is an unbounded-cardinality hole — every distinct
  id mints a new time series in the registry, the federation merge
  and the tsdb ring.  ``TenantAdmission.label()`` caps the set
  (first-N stable, rest folded into ``"other"``), so the static
  proxy for "bounded" is: the registering module contains at least
  one call whose attribute name is ``label``.

Only calls whose receiver is a registry (``metrics.…`` /
``registry.…``) with a literal string name are checked — direct
``Histogram(...)`` constructions are instance-local (not exported
families) and stay out of scope, as do dynamic names.
"""

import ast
import re

from veles_tpu.analysis.core import Finding, Pass, dotted, qualname_of

#: the exported-family naming convention (M501)
_NAME_RE = re.compile(r"^veles(_[a-z0-9]+)+$")

#: registry get-or-create methods and the receivers that make a call
#: a REGISTRY call (vs. numpy.histogram or a constructor)
_METHODS = ("counter", "gauge", "histogram")
_RECEIVERS = ("metrics", "registry")


def _labelnames(call):
    """The call's declared labelnames as a sorted tuple — () when
    omitted, None when dynamic (non-literal)."""
    node = None
    for kw in call.keywords:
        if kw.arg == "labelnames":
            node = kw.value
            break
    else:
        if len(call.args) >= 3:   # (name, help, labelnames)
            node = call.args[2]
    if node is None:
        return ()
    if isinstance(node, (ast.Tuple, ast.List)) and all(
            isinstance(e, ast.Constant) and isinstance(e.value, str)
            for e in node.elts):
        return tuple(sorted(e.value for e in node.elts))
    return None


class MetricsHygienePass(Pass):
    NAME = "metrics-hygiene"
    CODES = {
        "M501": "exported metric family name is not veles_-prefixed "
                "snake_case — invisible to the fleet federation "
                "rollups, dashboards and alert-rule selectors that "
                "key on the veles_ namespace",
        "M502": "metric family declared with different label sets "
                "across call sites — the registry honors only the "
                "FIRST registration, so the others are dead text "
                "whose .labels() calls can raise at runtime",
        "M503": "tenant-labeled metric family registered in a module "
                "with no cardinality-bounder .label() call — raw "
                "caller-chosen tenant ids mint unbounded label "
                "series; route values through "
                "TenantAdmission.label()",
    }

    def run(self, module, project):
        findings = []
        sites = project.shared.setdefault("metric_sites", {})
        tenant_decls = []
        has_bounder_call = False
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Attribute):
                continue
            if node.func.attr == "label":
                # any `<something>.label(...)` counts as routing
                # through the cardinality bounder (M503)
                has_bounder_call = True
            if node.func.attr not in _METHODS:
                continue
            recv = dotted(node.func.value)
            if recv is None \
                    or recv.split(".")[-1] not in _RECEIVERS:
                continue
            if not node.args or not isinstance(
                    node.args[0], ast.Constant) \
                    or not isinstance(node.args[0].value, str):
                continue
            name = node.args[0].value
            if not _NAME_RE.match(name):
                findings.append(self.finding(
                    module, node, "M501", qualname_of(node), name,
                    "metric family %r is not veles_-prefixed "
                    "snake_case (^veles(_[a-z0-9]+)+$) — rename it "
                    "into the exported namespace" % name))
            labels = _labelnames(node)
            if labels is not None:
                sites.setdefault(name, []).append(
                    (labels, module, node))
                if "tenant" in labels:
                    tenant_decls.append((name, node))
        if not has_bounder_call:
            for name, node in tenant_decls:
                findings.append(self.finding(
                    module, node, "M503", qualname_of(node), name,
                    "family %r carries a 'tenant' label but this "
                    "module never calls a cardinality bounder "
                    "(.label(...)) — raw tenant ids make label "
                    "cardinality unbounded; fold values through "
                    "TenantAdmission.label() first" % name))
        return findings

    def finalize(self, project):
        findings = []
        sites = project.shared.get("metric_sites", {})
        for name, decls in sorted(sites.items()):
            label_sets = sorted({labels for labels, _, _ in decls})
            if len(label_sets) <= 1:
                continue
            rendered = " vs ".join(str(tuple(s)) for s in label_sets)
            for labels, module, node in decls:
                findings.append(Finding(
                    code="M502", path=module.relpath,
                    line=node.lineno, col=node.col_offset,
                    context=qualname_of(node), detail=name,
                    message="family %r declared with inconsistent "
                            "label sets across call sites (%s) — "
                            "only the first registration wins; make "
                            "every site agree" % (name, rendered)))
        return findings
