"""The veles-lint passes.  Adding a pass: subclass
:class:`veles_tpu.analysis.core.Pass`, give every code a ``CODES``
entry, and append an instance to :data:`ALL_PASSES` — the runner,
docs and ``--list-codes`` pick it up from there."""

from veles_tpu.analysis.passes.config_keys import ConfigKeysPass
from veles_tpu.analysis.passes.donation import DonationPass
from veles_tpu.analysis.passes.fault_points import FaultPointsPass
from veles_tpu.analysis.passes.locks import LocksPass
from veles_tpu.analysis.passes.metrics_hygiene import \
    MetricsHygienePass
from veles_tpu.analysis.passes.purity import PurityPass

ALL_PASSES = (DonationPass(), PurityPass(), LocksPass(),
              ConfigKeysPass(), MetricsHygienePass(),
              FaultPointsPass())

ALL_CODES = {}
for _p in ALL_PASSES:
    ALL_CODES.update(_p.CODES)
