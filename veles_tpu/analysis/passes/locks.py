"""L-series — lock discipline in threaded modules.

The pass only looks at modules that import ``threading`` (the
prefetch pipeline, the serving scheduler, telemetry, the DCN
coordinator...).  Within those it reconstructs, per class:

- the **lock attributes** (``self._lock = threading.Lock()`` /
  ``RLock`` / ``Condition``), plus module-level locks;
- the **thread-side methods**: every ``threading.Thread(target=...)``
  entry point and everything reachable from one through ``self.m()``
  calls;
- every **attribute write** (``self.x = ...``, ``self.x[...] = ...``,
  mutating calls like ``self.x.append(...)``) and whether it happens
  under a ``with <lock>:`` block.  Methods named ``*_locked`` are
  treated as called-with-lock-held (the repo's convention).

The codes:

- **L301** — an attribute written both from a thread target and from
  other code, with at least one of those writes outside any lock.
- **L302** — a check-then-act on shared state outside a lock:
  ``if x in d: ... d[x] = ...``, lazy-init ``if self.x is None:
  self.x = ...`` (including the early-``return`` variant), and
  boolean latches ``if not self.x: self.x = True`` — the
  ``_cost_lock`` fix class from PR 3.

``__init__`` / ``init_unpickled`` writes are construction-time and
ignored.
"""

import ast

from veles_tpu.analysis.core import (
    Pass, call_name, dotted, parent_chain, with_lock_names)

_LOCK_FACTORIES = ("threading.Lock", "threading.RLock",
                   "threading.Condition")
_MUTATORS = ("append", "appendleft", "add", "remove", "discard",
             "pop", "popleft", "clear", "update", "extend",
             "setdefault", "insert")
_CTOR_METHODS = ("__init__", "init_unpickled", "__new__")


def _self_attr(node):
    """``x`` for ``self.x`` (exactly one level), else None."""
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


class _ClassModel:
    def __init__(self, node):
        self.node = node
        self.methods = {
            n.name: n for n in node.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        self.lock_attrs = set()
        self.thread_targets = set()

    def scan(self):
        for m in self.methods.values():
            for node in ast.walk(m):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                if name in _LOCK_FACTORIES:
                    assign = getattr(node, "_parent", None)
                    if isinstance(assign, ast.Assign):
                        for t in assign.targets:
                            attr = _self_attr(t)
                            if attr:
                                self.lock_attrs.add(attr)
                elif name and name.split(".")[-1] == "Thread":
                    for kw in node.keywords:
                        if kw.arg != "target":
                            continue
                        tgt = dotted(kw.value) or ""
                        if tgt.startswith("self."):
                            self.thread_targets.add(
                                tgt.split(".", 1)[1])
        return self

    def thread_side(self):
        """Methods reachable from a Thread target via self.m()."""
        seen = set(t for t in self.thread_targets
                   if t in self.methods)
        frontier = list(seen)
        while frontier:
            m = frontier.pop()
            for node in ast.walk(self.methods[m]):
                if isinstance(node, ast.Call):
                    callee = dotted(node.func) or ""
                    if callee.startswith("self."):
                        name = callee.split(".")[1]
                        if name in self.methods and name not in seen:
                            seen.add(name)
                            frontier.append(name)
        return seen


class LocksPass(Pass):
    NAME = "locks"
    CODES = {
        "L301": "attribute written from a Thread target and from "
                "other code without a common lock",
        "L302": "check-then-act on shared state outside a lock "
                "(if-in/lazy-init/latch races)",
    }

    def run(self, module, project):
        if not module.imports_threading:
            return []
        findings = []
        module_locks = self._module_locks(module.tree)
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef):
                model = _ClassModel(node).scan()
                findings.extend(self._check_class(
                    module, model, module_locks))
        return findings

    @staticmethod
    def _module_locks(tree):
        """Module- and class-body-level lock names (``_lock =
        threading.Lock()`` at either level)."""
        locks = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and call_name(node.value) in _LOCK_FACTORIES:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        locks.add(t.id)
        return locks

    # -- write collection -------------------------------------------------

    def _is_locked(self, node, model, module_locks, method):
        if method.name.endswith("_locked"):
            return True  # repo convention: caller holds the lock
        for held in with_lock_names(node):
            tail = held.split(".")[-1]
            if tail in model.lock_attrs or tail in module_locks \
                    or held in module_locks:
                return True
        return False

    def _attr_writes(self, method):
        """(attr, node) pairs for every write to a ``self.``
        attribute in ``method`` — assignments, subscript stores,
        deletes, and mutating calls (append/pop/...)."""
        out = []
        for node in ast.walk(method):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets \
                    if isinstance(node, ast.Assign) else [node.target]
                for t in targets:
                    attr = _self_attr(t)
                    if attr:
                        out.append((attr, node))
                    elif isinstance(t, ast.Subscript):
                        attr = _self_attr(t.value)
                        if attr:
                            out.append((attr, node))
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    if isinstance(t, ast.Subscript):
                        attr = _self_attr(t.value)
                        if attr:
                            out.append((attr, node))
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _MUTATORS:
                attr = _self_attr(node.func.value)
                if attr:
                    out.append((attr, node))
        return out

    # -- L301 -------------------------------------------------------------

    def _check_class(self, module, model, module_locks):
        findings = []
        thread_side = model.thread_side()
        if thread_side:
            findings.extend(self._check_shared_writes(
                module, model, module_locks, thread_side))
        findings.extend(self._check_check_then_act(
            module, model, module_locks))
        return findings

    def _check_shared_writes(self, module, model, module_locks,
                             thread_side):
        per_attr = {}   # attr -> {"thread": [...], "main": [...]}
        for name, method in model.methods.items():
            if name in _CTOR_METHODS:
                continue
            side = "thread" if name in thread_side else "main"
            for attr, node in self._attr_writes(method):
                if attr in model.lock_attrs:
                    continue
                locked = self._is_locked(node, model, module_locks,
                                         method)
                per_attr.setdefault(attr, {"thread": [], "main": []})[
                    side].append((node, locked, name))
        findings = []
        for attr, sides in sorted(per_attr.items()):
            if not sides["thread"] or not sides["main"]:
                continue
            unlocked = [(n, m) for n, lk, m in
                        sides["thread"] + sides["main"] if not lk]
            if not unlocked:
                continue
            node, method = unlocked[0]
            t_m = sorted({m for _, _, m in sides["thread"]})
            m_m = sorted({m for _, _, m in sides["main"]})
            findings.append(self.finding(
                module, node, "L301",
                "%s.%s" % (model.node.name, method), attr,
                "`self.%s` is written from the thread side (%s) AND "
                "from other code (%s) but this write holds no lock "
                "— guard every write with a common lock"
                % (attr, ", ".join(t_m), ", ".join(m_m))))
        return findings

    # -- L302 -------------------------------------------------------------

    def _check_check_then_act(self, module, model, module_locks):
        findings = []
        for name, method in model.methods.items():
            if name in _CTOR_METHODS:
                continue
            for node in ast.walk(method):
                if not isinstance(node, ast.If):
                    continue
                if self._is_locked(node, model, module_locks, method):
                    continue
                hit = self._ctca_pattern(node, method)
                if hit is not None:
                    attr, kind = hit
                    findings.append(self.finding(
                        module, node, "L302",
                        "%s.%s" % (model.node.name, name), attr,
                        "check-then-act (%s) on `self.%s` outside a "
                        "lock — another thread can interleave between "
                        "the test and the write" % (kind, attr)))
        return findings

    def _ctca_pattern(self, if_node, method):
        """(attr, kind) when ``if_node`` is a guarded write race."""
        test = if_node.test
        # if KEY in self.d / if KEY not in self.d  ... self.d[...] = v
        # (the write inside the If, or guarded by an early return)
        if isinstance(test, ast.Compare) and len(test.ops) == 1 \
                and isinstance(test.ops[0], (ast.In, ast.NotIn)):
            attr = _self_attr(test.comparators[0])
            if attr:
                if self._writes_attr_in(if_node, attr):
                    return attr, "membership test"
                if if_node.body and isinstance(
                        if_node.body[0], (ast.Return, ast.Raise)) \
                        and self._writes_attr_after(if_node, method,
                                                    attr):
                    return attr, "membership test"
        # if self.x is None / if self.x is not None / if not self.x /
        # if self.x   ->   self.x = ...
        attr = self._guarded_attr(test)
        if attr is None:
            return None
        if self._writes_attr_in(if_node, attr):
            return attr, "lazy-init"
        # early-return variant: if self.x is not None: return ;
        # ... self.x = ...   later in the same method
        if if_node.body and isinstance(if_node.body[0],
                                       (ast.Return, ast.Raise)) \
                and self._writes_attr_after(if_node, method, attr):
            return attr, "early-return guard"
        return None

    def _writes_attr_after(self, if_node, method, attr):
        end = getattr(if_node, "end_lineno", if_node.lineno)
        for node in ast.walk(method):
            if getattr(node, "lineno", 0) <= end:
                continue
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if _self_attr(t) == attr:
                        return True
                    if isinstance(t, ast.Subscript) \
                            and _self_attr(t.value) == attr:
                        return True
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _MUTATORS \
                    and _self_attr(node.func.value) == attr:
                return True
        return False

    @staticmethod
    def _guarded_attr(test):
        if isinstance(test, ast.Compare) and len(test.ops) == 1 \
                and isinstance(test.ops[0], (ast.Is, ast.IsNot)) \
                and isinstance(test.comparators[0], ast.Constant) \
                and test.comparators[0].value is None:
            return _self_attr(test.left)
        if isinstance(test, ast.UnaryOp) \
                and isinstance(test.op, ast.Not):
            return _self_attr(test.operand)
        return _self_attr(test)

    def _writes_attr_in(self, if_node, attr):
        for node in ast.walk(if_node):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if _self_attr(t) == attr:
                        return True
                    if isinstance(t, ast.Subscript) \
                            and _self_attr(t.value) == attr:
                        return True
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _MUTATORS \
                    and _self_attr(node.func.value) == attr:
                return True
        return False
