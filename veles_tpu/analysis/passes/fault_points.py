"""F-series — fault-injection point hygiene.

The :mod:`veles_tpu.faults` registry is only useful while its
injection surface stays *discoverable*: operators arm points by name
(``VELES_FAULTS="router.forward=..."``) against the table in
``docs/robustness.md``, and chaos tests grep the tree for the call
sites.  Both break silently — an undocumented point is unarmable by
anyone who didn't read the diff that added it, and a computed point
name (f-string, ``%``-format, concatenation) matches neither the doc
table nor a grep nor, reliably, the fnmatch patterns specs are
written against.  This pass checks both statically:

- **F601** — a literal ``faults.fire(...)`` point name that does not
  appear (backticked) in the ``docs/robustness.md`` fault-point
  table.  The doc is the operator's armed-points contract; every
  hazard site belongs in it.
- **F602** — a ``faults.fire(...)`` whose point argument is not a
  string literal.  Armed point names must be fnmatch-stable
  literals: dynamic VALUES belong in the ``key=`` argument (that is
  what scopes a spec to one replica/worker), never in the point.

Both forms of a fire site are recognized: the direct call
(``faults.fire("point", key)``) and the executor indirection the
router uses to keep hangs off the event loop
(``run_in_executor(None, faults.fire, "point", key)``).
"""

import ast
from pathlib import Path

from veles_tpu.analysis.core import Pass, dotted, qualname_of

#: where the armed-points contract lives, relative to the repo root
DOC_PATH = Path("docs") / "robustness.md"


def _fire_point_node(call):
    """The point-argument AST node of a ``faults.fire`` site, or
    None when ``call`` is not one.  Handles the direct call and the
    ``run_in_executor(None, faults.fire, <point>, ...)``
    indirection (the callable rides as an argument and the point is
    the argument after it)."""
    func = call.func
    if isinstance(func, ast.Attribute) and func.attr == "fire":
        recv = dotted(func.value)
        if recv is not None and recv.split(".")[-1] == "faults":
            return call.args[0] if call.args else None
    for i, arg in enumerate(call.args):
        if isinstance(arg, ast.Attribute) and arg.attr == "fire":
            recv = dotted(arg.value)
            if recv is not None \
                    and recv.split(".")[-1] == "faults" \
                    and i + 1 < len(call.args):
                return call.args[i + 1]
    return None


def _project_root(project):
    """The scanned tree's root: any module's absolute path with its
    repo-relative path stripped off the tail."""
    for m in project.modules:
        rel = Path(m.relpath).parts
        parts = Path(m.path).parts
        if len(parts) >= len(rel) and parts[-len(rel):] == rel:
            return Path(*parts[:-len(rel)])
    return None


class FaultPointsPass(Pass):
    NAME = "fault-points"
    CODES = {
        "F601": "faults.fire point is not documented in the "
                "docs/robustness.md fault-point table — an "
                "undocumented injection point is unarmable by "
                "operators and invisible to chaos-test greps",
        "F602": "faults.fire point name is not a string literal — "
                "armed points must be fnmatch-stable literals "
                "(dynamic values belong in the key= argument, "
                "which scopes specs to one caller)",
    }

    def run(self, module, project):
        findings = []
        sites = project.shared.setdefault("fault_fire_sites", [])
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            point = _fire_point_node(node)
            if point is None:
                continue
            if isinstance(point, ast.Constant) \
                    and isinstance(point.value, str):
                sites.append((point.value, module, node))
            else:
                findings.append(self.finding(
                    module, node, "F602", qualname_of(node),
                    ast.unparse(point)[:60],
                    "faults.fire point must be a string literal "
                    "(got %s) — put the dynamic part in key=, "
                    "keeping the injection surface documented and "
                    "greppable" % type(point).__name__))
        return findings

    def finalize(self, project):
        findings = []
        sites = project.shared.get("fault_fire_sites", [])
        if not sites:
            return findings
        root = _project_root(project)
        doc = root / DOC_PATH if root is not None else None
        try:
            text = doc.read_text()
        except (OSError, AttributeError):
            text = ""
        for point, module, node in sites:
            if "`%s`" % point in text:
                continue
            findings.append(self.finding(
                module, node, "F601", qualname_of(node), point,
                "fault point %r is missing from the %s fault-point "
                "table — document it (backticked) so operators can "
                "arm it" % (point, DOC_PATH.as_posix())))
        return findings
