"""D-series — donated-buffer / host-view aliasing.

On XLA:CPU the host/device boundary is *one allocation wide*:
``jax.device_put`` borrows small numpy buffers zero-copy, and
``numpy.asarray(device_array)`` returns a read-only view of the
device buffer.  Donating (``donate_argnums``) a buffer that the host
still references — or holding a host view across a step that donates
it — lets XLA reuse/free memory the host side reads or owns: the
nondeterministic glibc heap-corruption family documented against the
``models/gd.py`` span step (see ROUND6_NOTES.md).  The codes:

- **D101** — an argument passed at a donated position is read again
  after the call (the buffer is dead the moment the call dispatches).
- **D102** — a host view of a device buffer (``numpy.asarray`` over a
  ``devmem``-carrying expression) is RETAINED (stored on self / a
  global, or returned) instead of consumed transiently.
- **D103** — a module- or class-level strong reference to a jitted
  closure (``NAME = jax.jit(...)`` / ``track_jit(...)`` at import
  time) — the executable and everything its closure pins live for
  the process; prefer building lazily inside the owning object (the
  ``track_jit`` lifetime note).
"""

import ast

from veles_tpu.analysis.core import (
    Pass, call_name, dotted, parent_chain, qualname_of)
from veles_tpu.analysis.passes.purity import (
    _is_trackjit_name, is_jax_jit_call)


def _donate_spec(call):
    """(argnums, argnames) donated by a ``jax.jit`` call, or None."""
    nums, names = (), ()
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            nums = tuple(_const_ints(kw.value))
        elif kw.arg == "donate_argnames":
            names = tuple(_const_strs(kw.value))
    return (nums, names) if nums or names else None


def _const_ints(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant)
                and isinstance(e.value, int)]
    return []


def _const_strs(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant)
                and isinstance(e.value, str)]
    return []


def _donating_jit_calls(tree):
    """Every ``jax.jit(..., donate_argnums=...)`` call node with its
    donation spec."""
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and is_jax_jit_call(node):
            spec = _donate_spec(node)
            if spec is not None:
                out.append((node, spec))
    return out


def _enclosing_method(node):
    for p in parent_chain(node):
        if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return p
    return None


class DonationPass(Pass):
    NAME = "donation"
    CODES = {
        "D101": "argument at a donated position is read after the "
                "call (the donated buffer is already dead)",
        "D102": "host view of a device buffer retained (stored or "
                "returned) — aliases memory a later donated step may "
                "reuse or free",
        "D103": "module/class-level strong reference to a jitted "
                "closure (executable + closure pinned for the "
                "process lifetime)",
    }

    def run(self, module, project):
        findings = []
        findings.extend(self._check_read_after_donate(module))
        findings.extend(self._check_host_views(module))
        findings.extend(self._check_global_jit_refs(module))
        return findings

    # -- D101 -------------------------------------------------------------

    def _callable_specs(self, tree):
        """Donation specs reachable from call sites in this module:
        ``name`` -> (argnums, argnames), where name is a plain
        function name, ``self.attr``, or resolved one level through
        ``self.attr = self._build()`` / builders whose return value
        is a donating jit (the gd.py idiom)."""
        specs = {}
        # direct: X = [track_jit(...,] jax.jit(f, donate...) [)]
        # and builder methods whose return wraps a donating jit
        builders = {}
        for call, spec in _donating_jit_calls(tree):
            assign = ret = None
            for p in parent_chain(call):
                if isinstance(p, ast.Assign):
                    assign = p
                    break
                if isinstance(p, ast.Return):
                    ret = p
                    break
                if isinstance(p, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                    break
            if assign is not None:
                for t in assign.targets:
                    name = dotted(t)
                    if name:
                        specs[name] = spec
            elif ret is not None:
                method = _enclosing_method(ret)
                if method is not None:
                    builders[method.name] = spec
        # one hop: X = <builder>() / self.attr = self.<builder>()
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                callee = dotted(node.value.func) or ""
                bname = callee.split(".")[-1]
                if bname in builders and callee in (
                        bname, "self." + bname):
                    for t in node.targets:
                        name = dotted(t)
                        if name:
                            specs[name] = builders[bname]
        return specs

    def _check_read_after_donate(self, module):
        findings = []
        specs = self._callable_specs(module.tree)
        if not specs:
            return findings
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func)
            if name not in specs:
                continue
            argnums, argnames = specs[name]
            donated = []
            for i in argnums:
                if i < len(node.args):
                    donated.append(node.args[i])
            for kw in node.keywords:
                if kw.arg in argnames:
                    donated.append(kw.value)
            fn = _enclosing_method(node)
            if fn is None:
                continue
            stmt = node
            while getattr(stmt, "_parent", None) is not None \
                    and stmt._parent is not fn:
                stmt = stmt._parent
            for arg in donated:
                expr = dotted(arg)
                if not expr:
                    continue
                hit = self._load_after(fn, stmt, expr)
                if hit is not None:
                    findings.append(self.finding(
                        module, hit, "D101", qualname_of(node),
                        "%s->%s" % (name, expr),
                        "`%s` was donated to `%s` above (its buffer "
                        "is dead after dispatch) but is read again "
                        "here" % (expr, name)))
        return findings

    @staticmethod
    def _load_after(fn, call_stmt, expr):
        """First Load of dotted ``expr`` in ``fn`` lexically after
        ``call_stmt`` ends (assignments to it don't count; a
        multi-line call's own arguments are part of the call)."""
        line = getattr(call_stmt, "end_lineno", None) \
            or call_stmt.lineno
        best = None
        for node in ast.walk(fn):
            if not isinstance(node, (ast.Attribute, ast.Name)):
                continue
            if not isinstance(getattr(node, "ctx", None), ast.Load):
                continue
            if node.lineno <= line:
                continue
            if dotted(node) != expr:
                continue
            # skip loads that are just the target of a re-assignment
            # chain (`x.devmem = new` parses devmem as Store; inner
            # `x` is a Load — ignore prefix loads inside a Store)
            parent = getattr(node, "_parent", None)
            skip = False
            while isinstance(parent, ast.Attribute):
                if isinstance(parent.ctx, ast.Store):
                    skip = True
                    break
                parent = getattr(parent, "_parent", None)
            if skip:
                continue
            if best is None or node.lineno < best.lineno:
                best = node
        return best

    # -- D102 -------------------------------------------------------------

    @staticmethod
    def _mentions_devmem(node):
        for sub in ast.walk(node):
            if isinstance(sub, ast.Attribute) and "devmem" in sub.attr:
                return True
            if isinstance(sub, ast.Name) and "devmem" in sub.id:
                return True
        return False

    def _check_host_views(self, module):
        findings = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            if call_name(node) not in ("numpy.asarray", "np.asarray"):
                continue
            if not node.args or not self._mentions_devmem(node.args[0]):
                continue
            retained = None
            for p in parent_chain(node):
                if isinstance(p, ast.Assign):
                    for t in p.targets:
                        name = dotted(t)
                        if name and (name.startswith("self.")
                                     or _enclosing_method(p) is None):
                            retained = ("stored as `%s`" % name, name)
                    break
                if isinstance(p, ast.Return):
                    m = _enclosing_method(p)
                    retained = ("returned from `%s`"
                                % (m.name if m else "<module>"),
                                "return")
                    break
                if isinstance(p, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                    break
            if retained is None:
                continue  # transient consumption is the safe idiom
            how, detail = retained
            findings.append(self.finding(
                module, node, "D102", qualname_of(node), detail,
                "host view `numpy.asarray(<devmem>)` %s — it aliases "
                "the device buffer; a later donated step can reuse or "
                "free that memory while this view still reads it "
                "(copy with numpy.array, or detach before donation)"
                % how))
        return findings

    # -- D103 -------------------------------------------------------------

    def _check_global_jit_refs(self, module):
        findings = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Assign):
                continue
            if _enclosing_method(node) is not None:
                continue  # function-local jit builds own their lifetime
            culprit = None
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Call) and (
                        is_jax_jit_call(sub)
                        or _is_trackjit_name(call_name(sub))):
                    culprit = sub
                    break
            if culprit is None:
                continue
            targets = ", ".join(
                filter(None, (dotted(t) for t in node.targets)))
            findings.append(self.finding(
                module, node, "D103", qualname_of(node),
                targets or "<assign>",
                "module/class-level `%s = ...jit...` holds a strong "
                "reference to the jitted closure for the process "
                "lifetime — executables and closure captures can "
                "never be freed (track_jit lifetime note); build "
                "lazily inside the owning object instead" % targets))
        return findings
