"""T-series — jit purity.

Inside a traced function, Python runs ONCE (at trace time): side
effects silently stop repeating, host reads of traced values either
crash or bake a stale constant into the executable, and a ``jax.jit``
that never routes through ``telemetry.track_jit`` compiles outside
the registry's cost accounting.  The codes:

- **T201** — Python side effect inside a jitted function (``global``
  statement, ``print``/``open``/``input``, ``time.*``, stdlib
  ``random.*`` / ``numpy.random.*``, ``self.attr = ...`` stores).
- **T202** — tracer concretization: ``float()/int()/bool()`` or
  ``.item()/.tolist()`` on a non-constant value inside a jitted
  function (fails under jit, or silently freezes a trace-time value).
- **T203** — ``jax.jit`` site not wrapped by ``track_jit`` (the
  compile would escape ``veles_jit_*`` metrics and cost accounting;
  formerly tests/test_jit_guard.py).
- **T204** — a required stable entry-point registration
  (``track_jit("<name>", ...)``) is missing from its module — bench
  and the compile dashboards key on these names.
"""

import ast

from veles_tpu.analysis.core import (
    Pass, call_name, dotted, parent_chain, qualname_of)

#: (relpath, stable name) registrations that must exist — serving's
#: compiled entry points; an unregistered paged-attention jit would
#: silently escape cost accounting (formerly
#: test_jit_guard.SERVING_ENTRY_POINTS)
REQUIRED_REGISTRATIONS = (
    ("serving/engine.py", "serving.slot_step"),
    ("serving/engine.py", "serving.paged_step"),
    ("serving/engine.py", "serving.verify_step"),
    ("serving/engine.py", "serving.sample_first"),
    ("serving/engine.py", "serving.paged_step_tp"),
    ("serving/draft.py", "serving.draft_step"),
    ("serving/draft.py", "serving.draft_train"),
    ("serving/prefill.py", "serving.prefill"),
    ("serving/prefill.py", "serving.prefill_chunk"),
    ("serving/openai_api.py", "serving.embed_pool"),
    ("serving/kv_slots.py", "serving.kv_insert_row"),
    ("serving/kv_slots.py", "serving.kv_insert_blocks"),
    ("serving/kv_slots.py", "serving.kv_gather_blocks"),
    ("serving/kv_slots.py", "serving.kv_quant_insert_blocks"),
    ("serving/kv_slots.py", "serving.kv_quant_gather_blocks"),
    ("serving/kv_slots.py", "serving.kv_export_blocks"),
    ("serving/kv_slots.py", "serving.kv_import_blocks"),
)

def _is_trackjit_name(name):
    """``track_jit`` under any import alias (``telemetry.track_jit``,
    a leading-underscore local alias, ...)."""
    return bool(name) and name.split(".")[-1].lstrip("_") == "track_jit"


#: callables that concretize a traced value
_CONCRETIZERS = ("float", "int", "bool")
_CONCRETIZE_METHODS = ("item", "tolist")

#: dotted-prefix calls that are host side effects under trace
_EFFECT_PREFIXES = ("time.", "random.", "numpy.random.", "np.random.",
                    "os.")
_EFFECT_BUILTINS = ("print", "open", "input")


def is_jax_jit_call(node):
    """True for ``jax.jit(...)`` and ``functools.partial(jax.jit,
    ...)`` call nodes."""
    name = call_name(node)
    if name == "jax.jit":
        return True
    if name in ("functools.partial", "partial") and node.args:
        return dotted(node.args[0]) == "jax.jit"
    return False


def _is_jit_decorator(dec):
    if dotted(dec) == "jax.jit":
        return True
    return isinstance(dec, ast.Call) and is_jax_jit_call(dec)


def jit_sites(tree):
    """Every ``jax.jit`` occurrence: ``(node, kind)`` where kind is
    ``"call"`` (a Call expression) or ``"decorator"`` (on a def)."""
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and is_jax_jit_call(node):
            out.append((node, "call"))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _is_jit_decorator(dec):
                    out.append((node, "decorator"))
    return out


def jitted_functions(tree):
    """FunctionDef/Lambda nodes that get traced: jit-decorated defs,
    local defs passed to ``jax.jit(f, ...)`` by name, and lambdas
    inlined into a jit call.  Nested defs inside a traced function
    are traced too — callers should walk the returned nodes' full
    subtrees."""
    defs = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, []).append(node)
    jitted = []
    for node, kind in jit_sites(tree):
        if kind == "decorator":
            jitted.append(node)
            continue
        args = list(node.args)
        # functools.partial(jax.jit, ...) carries no function yet —
        # the wrapped def is found through its decorator form instead
        if call_name(node) in ("functools.partial", "partial"):
            continue
        if not args:
            continue
        target = args[0]
        if isinstance(target, ast.Lambda):
            jitted.append(target)
        elif isinstance(target, ast.Name):
            jitted.extend(defs.get(target.id, ()))
    return jitted


def _in_jitted(node, jitted_set):
    return any(p in jitted_set for p in parent_chain(node)) \
        or node in jitted_set


def _const_free(node):
    """False when the expression is trivially static (literals,
    ``.shape``/``.ndim``/``.dtype`` reads, ``len()``)."""
    if isinstance(node, ast.Constant):
        return False
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in (
                "shape", "ndim", "dtype"):
            return False
        if isinstance(sub, ast.Call) and dotted(sub.func) == "len":
            return False
    return True


class PurityPass(Pass):
    NAME = "purity"
    CODES = {
        "T201": "Python side effect inside a jitted function "
                "(runs once at trace time, then never again)",
        "T202": "tracer concretization (float/int/bool/.item on a "
                "traced value) inside a jitted function",
        "T203": "jax.jit site not routed through telemetry.track_jit "
                "(compiles escape veles_jit_* accounting)",
        "T204": "required stable track_jit entry-point registration "
                "missing from its module",
    }

    def run(self, module, project):
        findings = []
        for fn in set(jitted_functions(module.tree)):
            findings.extend(self._check_purity(module, fn))
        findings.extend(self._check_tracked(module))
        return findings

    # -- T201 / T202 -----------------------------------------------------

    def _check_purity(self, module, fn):
        findings = []
        for node in ast.walk(fn):
            if isinstance(node, ast.Global):
                findings.append(self.finding(
                    module, node, "T201", qualname_of(node),
                    "global:" + ",".join(node.names),
                    "`global %s` inside a jitted function — the "
                    "rebind happens at trace time only"
                    % ", ".join(node.names)))
            elif isinstance(node, ast.Call):
                findings.extend(self._check_call(module, node))
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets \
                    if isinstance(node, ast.Assign) else [node.target]
                for t in targets:
                    name = dotted(t)
                    if name and name.startswith("self."):
                        findings.append(self.finding(
                            module, node, "T201", qualname_of(node),
                            name,
                            "attribute store `%s = ...` inside a "
                            "jitted function mutates host state at "
                            "trace time only" % name))
        return findings

    def _check_call(self, module, node):
        name = dotted(node.func)
        if name is None:
            return []
        if name in _EFFECT_BUILTINS or any(
                name.startswith(p) for p in _EFFECT_PREFIXES):
            return [self.finding(
                module, node, "T201", qualname_of(node), name,
                "`%s(...)` inside a jitted function is a trace-time "
                "side effect (jax.random / in-graph ops are the "
                "traced equivalents)" % name)]
        if name in _CONCRETIZERS and node.args \
                and _const_free(node.args[0]):
            return [self.finding(
                module, node, "T202", qualname_of(node), name,
                "`%s(...)` on a traced value concretizes the tracer "
                "(ConcretizationTypeError, or a stale trace-time "
                "constant)" % name)]
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _CONCRETIZE_METHODS \
                and not node.args:
            return [self.finding(
                module, node, "T202", qualname_of(node),
                "." + node.func.attr,
                "`.%s()` on a traced value concretizes the tracer"
                % node.func.attr)]
        return []

    # -- T203 -------------------------------------------------------------

    def _check_tracked(self, module):
        findings = []
        rebound = self._trackjit_rebinds(module.tree)
        for node, kind in jit_sites(module.tree):
            if kind == "call" and self._is_decorator(node):
                continue  # reported once, as the decorator site
            if kind == "decorator":
                if node.name in rebound:
                    continue
                findings.append(self.finding(
                    module, node, "T203", qualname_of(node), node.name,
                    "jit-decorated `%s` is never rebound through "
                    "track_jit(name, ...) — its compiles escape the "
                    "registry" % node.name))
            else:
                if any(isinstance(p, ast.Call)
                       and _is_trackjit_name(call_name(p))
                       for p in parent_chain(node)):
                    continue
                findings.append(self.finding(
                    module, node, "T203", qualname_of(node), "jax.jit",
                    "jax.jit site not wrapped with track_jit(name, "
                    "jax.jit(...)) — compiles escape veles_jit_* "
                    "metrics and cost accounting"))
        return findings

    @staticmethod
    def _is_decorator(call):
        parent = getattr(call, "_parent", None)
        return isinstance(parent, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)) \
            and call in parent.decorator_list

    @staticmethod
    def _trackjit_rebinds(tree):
        """Names handed to a ``track_jit(...)`` call anywhere in the
        module — ``NAME = track_jit("...", NAME)`` module rebinds
        (ops/random.py, ops/gemm.py) and ``return track_jit("...",
        decorated)`` builder returns (models/generate.py)."""
        out = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and _is_trackjit_name(
                    call_name(node)):
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        out.add(arg.id)
        return out

    # -- T204 -------------------------------------------------------------

    def finalize(self, project):
        findings = []
        for relpath, name in REQUIRED_REGISTRATIONS:
            module = None
            for m in project.modules:
                if m.relpath.endswith(relpath):
                    module = m
                    break
            if module is None:  # subset scan — nothing to assert
                continue
            if 'track_jit("%s"' % name not in module.text:
                findings.append(self.finding(
                    module, module.tree, "T204", "<registry>", name,
                    "%s must register its compiled entry point as "
                    'track_jit("%s", jax.jit(...)) — bench and the '
                    "compile dashboards key on that name"
                    % (relpath, name)))
        return findings
