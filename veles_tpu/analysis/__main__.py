"""CLI runner: ``python -m veles_tpu.analysis [options] [paths...]``.

Exit codes: 0 clean (every finding fixed or baselined), 1 unbaselined
findings (or, under ``--strict``, stale baseline entries / parse
errors), 2 usage errors.  Default scan target is the ``veles_tpu``
package itself; the default baseline is ``analysis/baseline.txt``.
"""

import argparse
import sys
import time
from pathlib import Path

from veles_tpu.analysis import (
    ALL_CODES, ALL_PASSES, DEFAULT_BASELINE, analyze, format_entry,
    render_json, render_text)

PKG_ROOT = Path(__file__).resolve().parent.parent


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m veles_tpu.analysis",
        description="veles-lint: AST hazard analysis (donation "
                    "aliasing, jit purity, lock discipline, config "
                    "keys)")
    ap.add_argument("paths", nargs="*",
                    help="files/directories to scan (default: the "
                         "veles_tpu package)")
    ap.add_argument("--strict", action="store_true",
                    help="also fail on stale baseline entries and "
                         "file parse errors (the tier-1 gate mode)")
    ap.add_argument("--format", choices=("text", "json"),
                    default="text")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help="baseline file (default: %s)"
                         % DEFAULT_BASELINE)
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, baselined or not")
    ap.add_argument("--emit-baseline", action="store_true",
                    help="print ready-to-paste baseline lines for "
                         "the unbaselined findings and exit 0")
    ap.add_argument("--codes", default=None, metavar="PREFIXES",
                    help="comma-separated code/prefix filter "
                         "(e.g. 'L,T203')")
    ap.add_argument("--list-codes", action="store_true")
    args = ap.parse_args(argv)

    if args.list_codes:
        for code in sorted(ALL_CODES):
            print("%s  %s" % (code, ALL_CODES[code]))
        return 0

    paths = args.paths or [str(PKG_ROOT)]
    t0 = time.perf_counter()
    findings, fresh, stale, errors = analyze(
        paths, root=PKG_ROOT.parent,
        baseline=False if args.no_baseline else args.baseline)
    if args.codes:
        prefixes = tuple(p.strip() for p in args.codes.split(",")
                         if p.strip())
        findings = [f for f in findings
                    if f.code.startswith(prefixes)]
        fresh = [f for f in fresh if f.code.startswith(prefixes)]

    if args.emit_baseline:
        for f in fresh:
            print(format_entry(f))
        return 0

    if args.format == "json":
        print(render_json(findings, stale=stale, errors=errors))
    else:
        print(render_text(findings, stale=stale,
                          show_baselined=args.no_baseline))
        for path, err in errors:
            print("parse error: %s: %s" % (path, err),
                  file=sys.stderr)
        print("scanned in %.2fs" % (time.perf_counter() - t0),
              file=sys.stderr)

    if fresh:
        return 1
    if args.strict and (stale or errors):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
