"""Accepted-findings baseline.

A finding the team has looked at and deliberately accepts lives in
``baseline.txt`` next to this module, one per line::

    CODE path::context::detail  -- reason the pattern is deliberate

The key carries no line numbers, so unrelated edits don't churn the
file; the ``--`` separated reason is REQUIRED — a baseline entry
without a why is just a suppressed bug.  ``--strict`` additionally
fails on *stale* entries (keys matching no current finding): a stale
entry means the exception it documented is gone, and keeping it could
mask a future regression at the same site (the old
test_jit_guard.py allowlist-pruning rule, generalized).
"""

from pathlib import Path

DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.txt"


class BaselineError(ValueError):
    pass


def load_baseline(path=None):
    """{key: reason} from a baseline file (missing file = empty)."""
    path = Path(path) if path else DEFAULT_BASELINE
    entries = {}
    if not path.is_file():
        return entries
    for n, raw in enumerate(path.read_text().splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if "--" not in line:
            raise BaselineError(
                "%s:%d: baseline entry without a `-- reason`: %r"
                % (path, n, raw))
        key, reason = line.split("--", 1)
        key = " ".join(key.split())
        reason = reason.strip()
        if not reason:
            raise BaselineError(
                "%s:%d: empty reason for %r" % (path, n, key))
        entries[key] = reason
    return entries


def apply_baseline(findings, entries):
    """Mark baselined findings in place; returns (unbaselined
    findings, stale keys)."""
    used = set()
    for f in findings:
        reason = entries.get(f.key)
        if reason is not None:
            f.baselined = True
            f.reason = reason
            used.add(f.key)
    stale = sorted(set(entries) - used)
    fresh = [f for f in findings if not f.baselined]
    return fresh, stale


def format_entry(finding, reason="TODO: why is this deliberate?"):
    """The line to paste into baseline.txt for ``finding``."""
    return "%s  -- %s" % (finding.key, reason)
